"""Admission control subsystem (ISSUE 5): cost classifier, per-tenant
weighted fair queue, adaptive concurrency limiter, priority load
shedding, middleware + engine-host wiring, Retry-After behavior, the
failover interplay, the watch-hub recompute fusing satellite, and
caveat graceful degradation."""

import asyncio
import threading
import time

import pytest

from spicedb_kubeapi_proxy_tpu.admission import (
    BULK_CHECK,
    CHECK,
    LOOKUP_PREFILTER,
    WATCH_RECOMPUTE,
    WRITE_DTX,
    AdaptiveLimiter,
    AdmissionController,
    AdmissionRejected,
    classify_op,
    classify_request,
)
from spicedb_kubeapi_proxy_tpu.authz import AuthzDeps, authorize
from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import parse_request_info
from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest
from spicedb_kubeapi_proxy_tpu.rules import MapMatcher
from spicedb_kubeapi_proxy_tpu.rules.input import UserInfo
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

ALL_CLASSES = ("check", "bulk-check", "lookup-prefilter",
               "watch-recompute", "write-dtx")


def shed_counts():
    return {c: metrics.counter("admission_shed_total",
                               **{"class": c}).value
            for c in ALL_CLASSES}


def ctrl(limit=1.0, **kw):
    """A controller with a PINNED limit (min=initial=max) and no debt
    decay, so scheduling decisions are deterministic."""
    kw.setdefault("tenant_rate", 0.0)
    kw.setdefault("tenant_burst", 1e9)
    kw.setdefault("tenant_depth", 64)
    kw.setdefault("global_depth", 256)
    kw.setdefault("queue_timeout", 30.0)
    return AdmissionController(
        initial_concurrency=limit, min_concurrency=limit,
        max_concurrency=limit, **kw)


# -- classifier ---------------------------------------------------------------


def test_classify_op_and_shed_order():
    assert classify_op("check_bulk") is CHECK
    assert classify_op("check_bulk", 8) is BULK_CHECK
    assert classify_op("lookup_mask") is LOOKUP_PREFILTER
    assert classify_op("lookup_resources") is LOOKUP_PREFILTER
    assert classify_op("write_relationships") is WRITE_DTX
    assert classify_op("delete_relationships") is WRITE_DTX
    assert classify_op("watch_since") is WATCH_RECOMPUTE
    # control-plane ops are never gated
    for op in ("revision", "failover_state", "watch_subscribe",
               "mirror_subscribe", "object_ids", "exists"):
        assert classify_op(op) is None
    # shed order: watch ticks first, then lists, then checks; writes last
    assert WATCH_RECOMPUTE.priority < LOOKUP_PREFILTER.priority
    assert LOOKUP_PREFILTER.priority < CHECK.priority
    assert CHECK.priority == BULK_CHECK.priority
    assert CHECK.priority < WRITE_DTX.priority
    # weights scale with device cost
    assert LOOKUP_PREFILTER.weight > BULK_CHECK.weight > 0


def test_classify_request():
    matcher = MapMatcher.from_yaml(open("deploy/rules.yaml").read())

    def rules_for(verb, path, query=None):
        from spicedb_kubeapi_proxy_tpu.rules.matcher import RequestMeta

        info = parse_request_info(verb_to_method(verb), path, query or {})
        return matcher.match(RequestMeta.from_request(info))

    def verb_to_method(verb):
        return {"create": "POST", "delete": "DELETE"}.get(verb, "GET")

    assert classify_request(
        "create", rules_for("create", "/api/v1/namespaces")) is WRITE_DTX
    assert classify_request(
        "list", rules_for("list", "/api/v1/namespaces")) \
        is LOOKUP_PREFILTER
    assert classify_request(
        "watch", rules_for(
            "watch", "/api/v1/namespaces", {"watch": ["true"]})) \
        is WATCH_RECOMPUTE
    got = classify_request(
        "get", rules_for("get", "/api/v1/namespaces/x"))
    assert got in (CHECK, BULK_CHECK)


# -- fair queue ---------------------------------------------------------------


def test_immediate_admission_tracks_weighted_cost():
    c = ctrl(limit=8.0)
    t1 = c.acquire("a", CHECK)
    t2 = c.acquire("a", LOOKUP_PREFILTER)
    st = c.status()
    assert st["inflight"] == 2
    assert st["inflight_cost"] == 5.0  # 1 + 4
    t1.release()
    t2.release()
    t2.release()  # idempotent: no double credit
    st = c.status()
    assert st["inflight"] == 0 and st["inflight_cost"] == 0.0


def test_fair_queue_storm_tenant_cannot_starve():
    async def go():
        c = ctrl(limit=1.0)
        hold = await c.acquire_async("warm", CHECK)
        order = []

        async def waiter(tenant):
            t = await c.acquire_async(tenant, CHECK)
            order.append(tenant)
            t.release()

        # the storm tenant queues 8 requests BEFORE alice/bob queue 3
        # each: plain FIFO would serve all 8 first
        tasks = [asyncio.ensure_future(waiter("storm")) for _ in range(8)]
        await asyncio.sleep(0)
        tasks += [asyncio.ensure_future(waiter("alice")) for _ in range(3)]
        tasks += [asyncio.ensure_future(waiter("bob")) for _ in range(3)]
        await asyncio.sleep(0)
        assert c.status()["queued"] == 14
        hold.release()  # begin the drain chain
        await asyncio.wait_for(asyncio.gather(*tasks), 10)
        # weighted fair share: alice and bob are served round-robin with
        # the storm, not behind its whole backlog
        assert "alice" in order[:6] and "bob" in order[:6]
        assert order.count("storm") == 8  # nothing lost either
    asyncio.run(go())


def test_priority_shedding_evicts_lowest_class_first():
    async def go():
        before = shed_counts()
        c = ctrl(limit=1.0, global_depth=3, tenant_depth=3)
        hold = await c.acquire_async("hog", CHECK)
        results = {}

        async def waiter(name, tenant, cls):
            try:
                t = await c.acquire_async(tenant, cls)
                results[name] = "granted"
                t.release()
            except AdmissionRejected as e:
                results[name] = ("shed", e.retry_after)

        tasks = [asyncio.ensure_future(
            waiter(f"w{i}", f"wt{i}", WATCH_RECOMPUTE)) for i in range(3)]
        await asyncio.sleep(0)
        # queue full of watch recomputes; an arriving WRITE evicts the
        # NEWEST lowest-priority waiter instead of being rejected
        tasks.append(asyncio.ensure_future(
            waiter("write", "writer", WRITE_DTX)))
        await asyncio.sleep(0.01)
        assert results.get("w2", ("", 0))[0] == "shed"
        # an arriving watch tick outranks nothing: IT sheds
        tasks.append(asyncio.ensure_future(
            waiter("late-watch", "wtx", WATCH_RECOMPUTE)))
        await asyncio.sleep(0.01)
        assert results["late-watch"][0] == "shed"
        assert results["late-watch"][1] > 0  # Retry-After hint present
        hold.release()
        await asyncio.wait_for(asyncio.gather(*tasks), 10)
        assert results["write"] == "granted"
        assert results["w0"] == results["w1"] == "granted"
        after = shed_counts()
        # every rejection accounted, under its own class
        assert after["watch-recompute"] - before["watch-recompute"] == 2
        assert after["write-dtx"] == before["write-dtx"]
    asyncio.run(go())


def test_queue_timeout_sheds_never_hangs():
    c = ctrl(limit=1.0, queue_timeout=0.05)
    hold = c.acquire("hog", CHECK)
    before = shed_counts()
    t0 = time.monotonic()
    with pytest.raises(AdmissionRejected) as ei:
        c.acquire("victim", CHECK)
    elapsed = time.monotonic() - t0
    assert 0.04 <= elapsed < 2.0  # bounded: sheds at the timeout
    assert ei.value.retry_after > 0
    assert ei.value.dependency == "admission"
    after = shed_counts()
    assert after["check"] - before["check"] == 1
    hold.release()
    # capacity freed: the next acquire is immediate
    c.acquire("victim", CHECK).release()


def test_queue_depth_bounds():
    async def go():
        c = ctrl(limit=1.0, tenant_depth=2, global_depth=100,
                 queue_timeout=30.0)
        hold = await c.acquire_async("t", CHECK)
        tasks = [asyncio.ensure_future(c.acquire_async("t", CHECK))
                 for _ in range(2)]
        await asyncio.sleep(0)
        # third same-tenant, same-priority arrival overflows ITS queue
        with pytest.raises(AdmissionRejected):
            await c.acquire_async("t", CHECK)
        # ...but another tenant still queues fine
        other = asyncio.ensure_future(c.acquire_async("u", CHECK))
        await asyncio.sleep(0)
        assert c.status()["queued"] == 3
        hold.release()

        async def finish(fut):
            (await fut).release()

        # each waiter releases as soon as it is granted — grant order is
        # the fair queue's business, not the test's
        await asyncio.wait_for(
            asyncio.gather(*[finish(f) for f in tasks + [other]]), 10)
    asyncio.run(go())


def test_cancelled_waiter_leaks_nothing():
    """A handler task cancelled while its acquire is queued (client
    disconnect) must hand back its queue slot — or, if a grant raced
    in, the admitted capacity — never wedging the controller."""
    async def go():
        c = ctrl(limit=1.0)
        hold = await c.acquire_async("a", CHECK)
        # cancelled while QUEUED
        task = asyncio.ensure_future(c.acquire_async("b", CHECK))
        await asyncio.sleep(0)
        assert c.status()["queued"] == 1
        before = shed_counts()
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        assert c.status()["queued"] == 0
        # an abandoned wait is not an overload rejection
        assert shed_counts() == before
        # cancelled AFTER the grant raced in: the charged capacity must
        # be handed back by the cancellation path
        task2 = asyncio.ensure_future(c.acquire_async("b", CHECK))
        await asyncio.sleep(0)
        hold.release()  # grants task2's waiter synchronously
        task2.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task2
        st = c.status()
        assert st["inflight"] == 0 and st["inflight_cost"] == 0.0
        # not wedged: a fresh acquire admits immediately
        (await c.acquire_async("b", CHECK)).release()
    asyncio.run(go())


def test_cancel_of_blocking_head_drains_fitting_waiters():
    """Removing a too-heavy queue head (timeout or cancellation) must
    drain immediately: a lighter request that fits under the limit may
    not sit until an unrelated release — or shed spuriously at its own
    timeout — while capacity is free."""
    async def go():
        c = ctrl(limit=4.0, queue_timeout=5.0)
        a = await c.acquire_async("a", BULK_CHECK)  # 2 units
        b = await c.acquire_async("b", CHECK)  # 3 units total
        big = asyncio.ensure_future(
            c.acquire_async("c", LOOKUP_PREFILTER))  # 4: does not fit
        await asyncio.sleep(0)
        small = asyncio.ensure_future(
            c.acquire_async("d", CHECK))  # fits (3+1<=4), behind head
        await asyncio.sleep(0)
        assert c.status()["queued"] == 2
        big.cancel()
        with pytest.raises(asyncio.CancelledError):
            await big
        # granted promptly off the cancellation drain — NO release ran
        t = await asyncio.wait_for(small, 1.0)
        t.release()
        a.release()
        b.release()
        assert c.status()["inflight"] == 0
    asyncio.run(go())


# -- adaptive limiter ---------------------------------------------------------


def test_limiter_grows_when_healthy_and_saturated():
    lim = AdaptiveLimiter(initial=32, min_limit=4, max_limit=64,
                          warmup=5, cooldown=2)
    for _ in range(40):
        lim.observe(0.010, inflight_cost=lim.limit)  # healthy + full
    assert lim.limit > 32
    grown = lim.limit
    # unsaturated healthy traffic learns nothing
    for _ in range(40):
        lim.observe(0.010, inflight_cost=0.0)
    assert lim.limit == grown


def test_limiter_grows_under_heavy_weight_saturation():
    """Utilization is sampled BEFORE the released weight is handed back:
    a system saturated purely by weight-4 lookups must still be able to
    probe headroom (post-decrement sampling could never reach the
    threshold for heavy classes, ratcheting the limit down only)."""
    lim = AdaptiveLimiter(initial=8, min_limit=4, max_limit=32,
                          warmup=5, cooldown=2)
    c = AdmissionController(tenant_rate=0.0, tenant_burst=1e9,
                            queue_timeout=5.0, limiter=lim)
    for _ in range(30):
        t1 = c.acquire("a", LOOKUP_PREFILTER)
        t2 = c.acquire("b", LOOKUP_PREFILTER)  # 8 units: saturated
        t1.release()
        t2.release()
    assert lim.limit > 8


def test_limiter_backs_off_when_latency_detaches():
    lim = AdaptiveLimiter(initial=32, min_limit=4, max_limit=64,
                          warmup=5, cooldown=2)
    for _ in range(10):
        lim.observe(0.010, inflight_cost=lim.limit)
    top = lim.limit
    for _ in range(60):
        lim.observe(0.200, inflight_cost=lim.limit)  # 20x the baseline
    assert lim.limit <= top * 0.5
    assert lim.limit >= 4  # never below the floor


# -- middleware wiring --------------------------------------------------------

DEPLOY_RULES = open("deploy/rules.yaml").read()


class WorkflowSpy:
    """Records dual-write enqueues; a SHED write must never reach it."""

    def __init__(self):
        self.created = 0

    async def create_instance(self, mode, input):
        self.created += 1
        return "iid"

    async def get_result(self, iid, timeout):  # pragma: no cover
        raise AssertionError("unexpected workflow wait")


async def _upstream_200(req):
    from spicedb_kubeapi_proxy_tpu.proxy.types import json_response

    return json_response(200, {"kind": "NamespaceList", "items": []})


def _request(method, path, user="alice", body=None, query=None):
    import json as _json

    query = query or {}
    return ProxyRequest(
        method=method, path=path, query=query,
        headers={"Content-Type": "application/json"},
        body=_json.dumps(body).encode() if body is not None else b"",
        user=UserInfo(name=user),
        request_info=parse_request_info(method, path, query))


def test_shed_write_returns_503_retry_after_and_never_enqueues():
    async def go():
        c = ctrl(limit=1.0, queue_timeout=0.05)
        hold = c.acquire("hog", CHECK)
        spy = WorkflowSpy()
        deps = AuthzDeps(matcher=MapMatcher.from_yaml(DEPLOY_RULES),
                         engine=Engine(), upstream=_upstream_200,
                         workflow=spy, admission=c)
        before = shed_counts()
        m0 = metrics.counter("proxy_dependency_unavailable_total",
                             dependency="admission").value
        resp = await authorize(_request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "x"}}), deps)
        assert resp.status == 503
        assert int(resp.headers["Retry-After"]) >= 1
        # the write was shed BEFORE any durable side effect
        assert spy.created == 0
        after = shed_counts()
        assert after["write-dtx"] - before["write-dtx"] == 1
        assert metrics.counter("proxy_dependency_unavailable_total",
                               dependency="admission").value == m0 + 1
        hold.release()
    asyncio.run(go())


def test_admitted_request_flows_and_releases():
    async def go():
        c = ctrl(limit=8.0)
        e = Engine()
        e.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:dev#creator@user:alice"))])
        deps = AuthzDeps(matcher=MapMatcher.from_yaml(DEPLOY_RULES),
                         engine=e, upstream=_upstream_200, admission=c)
        resp = await authorize(
            _request("GET", "/api/v1/namespaces/dev"), deps)
        assert resp.status == 200
        assert c.status()["inflight"] == 0  # ticket released
        # denial also releases
        resp = await authorize(
            _request("GET", "/api/v1/namespaces/dev", user="bob"), deps)
        assert resp.status == 403
        assert c.status()["inflight"] == 0
    asyncio.run(go())


def test_admission_vs_not_leader_distinguishable_in_metrics():
    from spicedb_kubeapi_proxy_tpu.engine.remote import NotLeaderError

    class NotLeaderEngine:
        def check_bulk(self, items, now=None, context=None):
            raise NotLeaderError()

    async def go():
        # leg 1: an engine mid-failover fails closed as engine-leader
        deps = AuthzDeps(matcher=MapMatcher.from_yaml(DEPLOY_RULES),
                         engine=NotLeaderEngine(),
                         upstream=_upstream_200)
        leader0 = metrics.counter("proxy_dependency_unavailable_total",
                                  dependency="engine-leader").value
        adm0 = metrics.counter("proxy_dependency_unavailable_total",
                               dependency="admission").value
        resp = await authorize(
            _request("GET", "/api/v1/namespaces/dev"), deps)
        assert resp.status == 503 and "Retry-After" in resp.headers
        # leg 2: admission sheds the same request shape
        c = ctrl(limit=1.0, queue_timeout=0.05)
        hold = c.acquire("hog", CHECK)
        deps2 = AuthzDeps(matcher=MapMatcher.from_yaml(DEPLOY_RULES),
                          engine=Engine(), upstream=_upstream_200,
                          admission=c)
        resp2 = await authorize(
            _request("GET", "/api/v1/namespaces/dev"), deps2)
        assert resp2.status == 503 and "Retry-After" in resp2.headers
        hold.release()
        # the two Retry-After sources tick SEPARATE dependency labels
        assert metrics.counter("proxy_dependency_unavailable_total",
                               dependency="engine-leader").value \
            == leader0 + 1
        assert metrics.counter("proxy_dependency_unavailable_total",
                               dependency="admission").value == adm0 + 1
    asyncio.run(go())


# -- engine-host wiring -------------------------------------------------------


def test_engine_server_sheds_and_breaker_stays_closed():
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        EngineServer,
        RemoteEngine,
    )
    from spicedb_kubeapi_proxy_tpu.utils.resilience import STATE_CLOSED

    e = Engine()
    c = AdmissionController(
        initial_concurrency=1, min_concurrency=1, max_concurrency=1,
        tenant_rate=0.0, tenant_burst=1e9, queue_timeout=0.05,
        dependency="engine-admission")
    hold = c.acquire("hog", CHECK)

    async def go():
        server = EngineServer(e, admission=c)
        port = await server.start()
        remote = RemoteEngine("127.0.0.1", port)
        try:
            before = shed_counts()
            with pytest.raises(AdmissionRejected) as ei:
                await asyncio.to_thread(remote.check_bulk, [CheckItem(
                    "namespace", "dev", "view", "user", "alice")])
            assert ei.value.retry_after > 0
            assert ei.value.dependency == "engine-admission"
            # a shed is a healthy host saying "not now", NOT a transport
            # failure: the client breaker must stay closed
            assert remote.breaker.state == STATE_CLOSED
            after = shed_counts()
            assert after["check"] - before["check"] >= 1
            # control-plane ops are never gated, even while saturated
            assert await asyncio.to_thread(
                remote.failover_state) is not None
            # capacity freed -> the same op admits
            hold.release()
            got = await asyncio.to_thread(remote.check_bulk, [CheckItem(
                "namespace", "dev", "view", "user", "alice")])
            assert got == [False]
        finally:
            remote.close()
            await server.stop()
    asyncio.run(go())


def test_role_gate_wins_over_admission_so_shed_writes_never_apply():
    """Failover interplay: on a non-leader the not_leader rejection must
    win (it re-aims the client), and on a saturated leader a shed write
    must leave the store untouched — never acked, never applied."""
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        EngineServer,
        NotLeaderError,
        RemoteEngine,
    )

    e = Engine()
    c = AdmissionController(
        initial_concurrency=1, min_concurrency=1, max_concurrency=1,
        tenant_rate=0.0, tenant_burst=1e9, queue_timeout=0.05,
        dependency="engine-admission")
    hold = c.acquire("hog", CHECK)
    role = {"role": "follower", "term": 3, "revision": 0,
            "peer_id": 1, "lag": 0}

    async def go():
        server = EngineServer(e, admission=c,
                              failover_status=lambda: dict(role))
        port = await server.start()
        remote = RemoteEngine("127.0.0.1", port)
        rel = parse_relationship("namespace:dev#creator@user:alice")
        try:
            rev0 = e.revision
            # follower: not_leader, NOT admission (even while saturated)
            with pytest.raises(NotLeaderError):
                await asyncio.to_thread(
                    remote.write_relationships, [WriteOp("touch", rel)])
            # leader but saturated: the write sheds pre-dispatch
            role["role"] = "leader"
            with pytest.raises(AdmissionRejected):
                await asyncio.to_thread(
                    remote.write_relationships, [WriteOp("touch", rel)])
            assert e.revision == rev0  # nothing applied, nothing acked
            hold.release()
            rev = await asyncio.to_thread(
                remote.write_relationships, [WriteOp("touch", rel)])
            assert rev > rev0
        finally:
            remote.close()
            await server.stop()
    asyncio.run(go())


# -- readyz surfacing ---------------------------------------------------------


def test_readyz_reports_admission_state():
    from spicedb_kubeapi_proxy_tpu.proxy.server import Server

    async def go():
        c = ctrl(limit=4.0)
        deps = AuthzDeps(matcher=MapMatcher.from_yaml(DEPLOY_RULES),
                         engine=Engine(), upstream=_upstream_200,
                         admission=c)
        srv = Server(deps)
        resp = await srv.handle(_request("GET", "/readyz"))
        assert resp.status == 200
        body = resp.body.decode()
        assert "admission:" in body and "limit=4.0" in body
        assert "queued=0" in body
    asyncio.run(go())


# -- watch hub: recompute fusing (satellite) ---------------------------------


def test_watchhub_groups_fuse_into_batched_dispatches():
    from spicedb_kubeapi_proxy_tpu.authz.watchhub import WatchHub
    from spicedb_kubeapi_proxy_tpu.rules.input import ResolveInput
    from spicedb_kubeapi_proxy_tpu.rules.matcher import RequestMeta

    e = Engine()
    e.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:dev#viewer@user:u0"))])
    matcher = MapMatcher.from_yaml(DEPLOY_RULES)
    info = parse_request_info("GET", "/api/v1/namespaces",
                              {"watch": ["true"]})
    rules = matcher.match(RequestMeta.from_request(info))
    pf = next(p for r in rules for p in r.pre_filters)

    async def go():
        hub = WatchHub(e, poll_interval=0.01)
        handles = []
        for i in range(6):
            input = ResolveInput.create(info, UserInfo(name=f"u{i}"))
            handles.append(await hub.register(pf, input))
        b0 = metrics.counter("engine_lookup_batches_total").value
        n0 = metrics.counter("engine_lookups_total").value
        # ONE write batch triggers all 6 (rule, subject) groups
        await asyncio.to_thread(e.write_relationships, [WriteOp(
            "touch",
            parse_relationship("namespace:dev#viewer@user:u1"))])

        async def drain(h):
            while True:
                item = await asyncio.wait_for(h.queue.get(), 10)
                if item[0] == "allowed":
                    return
                assert item[0] != "error", item

        await asyncio.gather(*[drain(h) for h in handles])
        batches = metrics.counter(
            "engine_lookup_batches_total").value - b0
        lookups = metrics.counter("engine_lookups_total").value - n0
        # 6 group recomputes fused into shared dispatches (VERDICT Weak
        # #3: pre-fusing this was 6 independent fixpoints). Scheduling
        # jitter may split the window once or twice, but fusing must cut
        # the dispatch count at least in half
        assert lookups == 6
        assert 1 <= batches <= 3
        for h in handles:
            await hub.unregister(h)
    asyncio.run(go())


# -- caveat graceful degradation (satellite) ---------------------------------


def test_caveats_load_and_enforce_conditionally():
    from spicedb_kubeapi_proxy_tpu.engine.engine import SchemaViolation
    from spicedb_kubeapi_proxy_tpu.models.bootstrap import parse_bootstrap
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship

    b = parse_bootstrap("""
schema: |-
  caveat on_tuesday(day: string) { day == "tuesday" }
  definition user {}
  definition doc {
    relation viewer: user with on_tuesday and expiration | user
    permission view = viewer
  }
relationships: |-
  doc:readme#viewer@user:alice
  doc:readme#viewer@user:bob[on_tuesday]
""")
    assert "doc" in b.schema.definitions
    assert "on_tuesday" in b.schema.caveat_defs
    # caveated tuples LOAD (no more exclusion) and are enforced by the
    # device-side caveat VM: grant with satisfying context, deny with a
    # non-satisfying one, fail-closed deny on missing context
    assert len(b.relationships) == 2
    e = Engine(schema=b.schema)
    for r in b.relationships:
        e.write_relationships([WriteOp("touch", r)])
    assert e.check(CheckItem("doc", "readme", "view", "user", "alice"))
    bob = CheckItem("doc", "readme", "view", "user", "bob")
    assert e.check(bob, context={"day": "tuesday"})
    assert not e.check(bob, context={"day": "monday"})
    assert not e.check(bob)  # missing context: fail closed
    assert e.lookup_resources("doc", "view", "user", "bob") == []
    assert e.lookup_resources("doc", "view", "user", "bob",
                              context={"day": "tuesday"}) == ["readme"]
    # the write path accepts DECLARED caveats but still refuses
    # undeclared ones and contexts that don't type-check
    e.write_relationships([WriteOp("touch", Relationship(
        "doc", "x", "viewer", "user", "eve", None, None, "on_tuesday"))])
    with pytest.raises(SchemaViolation):
        e.write_relationships([WriteOp("touch", Relationship(
            "doc", "x", "viewer", "user", "eve", None, None,
            "no_such_caveat"))])
    with pytest.raises(SchemaViolation):
        # "tz" is not a parameter of on_tuesday(day string)
        e.write_relationships([WriteOp("touch", Relationship(
            "doc", "y", "viewer", "user", "eve", None, None,
            "on_tuesday", '{"tz":"utc"}'))])


def test_caveat_context_with_nested_brackets_parses_and_loads():
    from spicedb_kubeapi_proxy_tpu.models.bootstrap import parse_bootstrap

    # JSON-array context carries ']' inside the bracket: the lenient
    # context grammar must span it, and the context round-trips
    r = parse_relationship(
        'doc:1#viewer@user:a[ip_allowlist:{"ips":["10.0.0.0/8"]}]')
    assert r.caveat == "ip_allowlist"
    assert r.context_dict() == {"ips": ["10.0.0.0/8"]}
    r2 = parse_relationship(
        'doc:1#viewer@user:a[c:{"x":[1]}]'
        '[expiration:2030-01-01T00:00:00Z]')
    assert r2.caveat == "c" and r2.expiration is not None
    b = parse_bootstrap("""
schema: |-
  caveat ip_allowlist(ip ipaddress, ips list<ipaddress>) { ip in ips }
  definition user {}
  definition doc {
    relation viewer: user | user with ip_allowlist
    permission view = viewer
  }
relationships: |-
  doc:1#viewer@user:ok
  doc:1#viewer@user:cond[ip_allowlist:{"ips":["10.0.0.0/8"]}]
""")
    # conditional grants now LOAD with their contexts (enforced by the
    # caveat VM at check time) instead of being excluded
    assert [str(r) for r in b.relationships] == [
        "doc:1#viewer@user:ok",
        'doc:1#viewer@user:cond[ip_allowlist:{"ips":["10.0.0.0/8"]}]']
    # an UNDECLARED bracket trait is far more likely a typo (e.g.
    # [expiry:...] for [expiration:...]): refuse loudly rather than
    # silently dropping the grant as a phantom caveat
    with pytest.raises(ValueError, match="unknown trait"):
        parse_bootstrap("""
schema: |-
  definition user {}
  definition doc {
    relation viewer: user
    permission view = viewer
  }
relationships: |-
  doc:1#viewer@user:oops[expiry:2030-01-01T00:00:00Z]
""")
    # same guard at the schema level: a misspelled trait on a relation
    # is an error, not a phantom caveat
    from spicedb_kubeapi_proxy_tpu.models.schema import (
        SchemaError,
        parse_schema,
    )

    with pytest.raises(SchemaError, match="unknown trait"):
        parse_schema("""
definition user {}
definition doc { relation viewer: user with expirations }
""")


def test_upstream_wait_not_billed_to_engine_limiter():
    """The ticket is released before upstream-dominated tails: a slow
    kube-apiserver must not occupy device budget or feed the limiter."""
    from spicedb_kubeapi_proxy_tpu.proxy.types import json_response

    async def go():
        c = ctrl(limit=8.0)
        e = Engine()
        e.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:dev#creator@user:alice"))])
        seen_inflight = []

        async def upstream(req):
            seen_inflight.append(c.status()["inflight"])
            return json_response(200, {"kind": "Namespace",
                                       "metadata": {"name": "dev"}})

        deps = AuthzDeps(matcher=MapMatcher.from_yaml(DEPLOY_RULES),
                         engine=e, upstream=upstream, admission=c)
        # GET with checks only (no postchecks in deploy rules): the
        # ticket must already be released when the upstream runs
        resp = await authorize(
            _request("GET", "/api/v1/namespaces/dev"), deps)
        assert resp.status == 200
        assert seen_inflight == [0]
        # LIST rides a prefilter that OVERLAPS the upstream: held there
        resp = await authorize(
            _request("GET", "/api/v1/namespaces"), deps)
        assert resp.status == 200
        assert seen_inflight[1] == 1
        assert c.status()["inflight"] == 0  # and released at the end
    asyncio.run(go())


def test_cached_hits_do_not_feed_the_limiter():
    """A fully-cached verdict dispatched nothing: its (floor-clamped)
    span must not feed the limiter's baseline, or repeat-heavy cache-hit
    traffic would pin the baseline at the floor and make ordinary device
    latency read as congestion."""
    async def go():
        c = ctrl(limit=8.0)
        e = Engine()
        e.enable_decision_cache()
        e.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:dev#creator@user:alice"))])
        deps = AuthzDeps(matcher=MapMatcher.from_yaml(DEPLOY_RULES),
                         engine=e, upstream=_upstream_200, admission=c)
        req = lambda: _request("GET", "/api/v1/namespaces/dev")  # noqa: E731
        assert (await authorize(req(), deps)).status == 200  # miss
        s1 = c.limiter.snapshot()["samples"]
        assert s1 >= 1
        for _ in range(5):
            assert (await authorize(req(), deps)).status == 200  # hits
        assert c.limiter.snapshot()["samples"] == s1
    asyncio.run(go())


def test_caveat_tuple_string_round_trip():
    r = parse_relationship(
        "doc:readme#viewer@user:bob[c1][expiration:2030-01-01T00:00:00Z]")
    assert r.caveat == "c1" and r.expiration is not None
    assert str(r) == \
        "doc:readme#viewer@user:bob[c1][expiration:2030-01-01T00:00:00Z]"
    # plain expiration tuples are untouched by the caveat grammar
    r2 = parse_relationship(
        "doc:readme#viewer@user:bob[expiration:2030-01-01T00:00:00Z]")
    assert r2.caveat is None and r2.expiration is not None


# -- options ------------------------------------------------------------------


def test_options_validate_admission_flags():
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options,
        OptionsError,
    )

    def opts(**kw):
        return Options(rule_content=DEPLOY_RULES, upstream=object(),
                       admission=True, **kw)

    opts().validate()
    with pytest.raises(OptionsError):
        opts(admission_min_concurrency=64.0,
             admission_initial_concurrency=8.0).validate()
    with pytest.raises(OptionsError):
        opts(admission_queue_timeout=0.0).validate()
    with pytest.raises(OptionsError):
        opts(admission_queue_depth=0).validate()
    with pytest.raises(OptionsError):
        opts(admission_tenant_rate=-1.0).validate()


def test_options_complete_wires_admission_into_deps():
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    cfg = Options(rule_content=DEPLOY_RULES, upstream=_upstream_200,
                  admission=True,
                  workflow_database_path=":memory:").complete()
    assert cfg.deps.admission is not None
    assert cfg.deps.admission.status()["limit"] == 32.0
    # default off: byte-identical to the pre-admission proxy
    cfg2 = Options(rule_content=DEPLOY_RULES, upstream=_upstream_200,
                   workflow_database_path=":memory:").complete()
    assert cfg2.deps.admission is None


# -- concurrency stress: fairness under real threads -------------------------


def test_fairness_under_thread_concurrency():
    """A storm tenant hammering from many threads cannot starve two
    polite tenants: with capacity 1 and a fair queue, grants interleave
    by debt, so the polite tenants complete their (small) workloads in
    bounded time even while the storm keeps the queue full."""
    c = ctrl(limit=1.0, queue_timeout=5.0)
    done = {"storm": 0, "alice": 0, "bob": 0}
    lock = threading.Lock()
    stop = threading.Event()

    def storm():
        while not stop.is_set():
            try:
                t = c.acquire("storm", CHECK)
            except AdmissionRejected:
                continue
            time.sleep(0.001)
            t.release()
            with lock:
                done["storm"] += 1

    def polite(name, n=10):
        for _ in range(n):
            t = c.acquire(name, CHECK)
            time.sleep(0.001)
            t.release()
            with lock:
                done[name] += 1

    storms = [threading.Thread(target=storm) for _ in range(6)]
    for t in storms:
        t.start()
    time.sleep(0.05)  # let the storm own the queue first
    p1 = threading.Thread(target=polite, args=("alice",))
    p2 = threading.Thread(target=polite, args=("bob",))
    t0 = time.monotonic()
    p1.start()
    p2.start()
    p1.join(timeout=10)
    p2.join(timeout=10)
    elapsed = time.monotonic() - t0
    stop.set()
    for t in storms:
        t.join(timeout=10)
    assert done["alice"] == 10 and done["bob"] == 10
    # fair share: ~every third grant went to a polite tenant, so the 10
    # ops complete in roughly 30 service times, not behind the storm
    assert elapsed < 5.0
