"""Upstream connection resolution: kubeconfig files and in-cluster config
(reference pkg/proxy/options.go:223-263,429-449)."""

import asyncio
import base64
import json

import pytest

from spicedb_kubeapi_proxy_tpu.proxy.kubeconfig import (
    KubeconfigError,
    in_cluster_available,
    in_cluster_config,
    load_kubeconfig,
)
from spicedb_kubeapi_proxy_tpu.proxy.options import Options, OptionsError

from fake_kube import FakeKube, serve_upstream


def write_kubeconfig(tmp_path, server="https://kube.example:6443",
                     extra_user="", extra_cluster="", name="kc.yaml",
                     current="main"):
    p = tmp_path / name
    p.write_text(f"""
apiVersion: v1
kind: Config
current-context: {current}
contexts:
- name: main
  context:
    cluster: prod
    user: admin
- name: alt
  context:
    cluster: staging
    user: dev
clusters:
- name: prod
  cluster:
    server: {server}
{extra_cluster}
- name: staging
  cluster:
    server: https://staging.example:6443
    insecure-skip-tls-verify: true
users:
- name: admin
  user:
    token: sekrit-token
{extra_user}
- name: dev
  user: {{}}
""")
    return str(p)


def test_kubeconfig_current_context(tmp_path):
    uc = load_kubeconfig(write_kubeconfig(tmp_path))
    assert uc.url == "https://kube.example:6443"
    assert uc.token == "sekrit-token"
    assert not uc.insecure_skip_verify


def test_kubeconfig_explicit_context(tmp_path):
    uc = load_kubeconfig(write_kubeconfig(tmp_path), context="alt")
    assert uc.url == "https://staging.example:6443"
    assert uc.token is None
    assert uc.insecure_skip_verify


def test_kubeconfig_inline_data_materialized(tmp_path):
    ca = base64.b64encode(b"CA PEM HERE").decode()
    cert = base64.b64encode(b"CERT PEM").decode()
    key = base64.b64encode(b"KEY PEM").decode()
    path = write_kubeconfig(
        tmp_path,
        extra_cluster=f"    certificate-authority-data: {ca}\n",
        extra_user=(f"    client-certificate-data: {cert}\n"
                    f"    client-key-data: {key}\n"))
    uc = load_kubeconfig(path)
    assert open(uc.ca_file, "rb").read() == b"CA PEM HERE"
    assert open(uc.client_cert, "rb").read() == b"CERT PEM"
    assert open(uc.client_key, "rb").read() == b"KEY PEM"


def test_kubeconfig_errors(tmp_path):
    with pytest.raises(KubeconfigError, match="no context"):
        load_kubeconfig(write_kubeconfig(tmp_path), context="nope")
    with pytest.raises(KubeconfigError, match="no current-context"):
        load_kubeconfig(write_kubeconfig(tmp_path, current=""))


def test_in_cluster_config(tmp_path, monkeypatch):
    sa = tmp_path / "sa"
    sa.mkdir()
    (sa / "token").write_text("pod-token\n")
    (sa / "ca.crt").write_text("CA")
    env = {"KUBERNETES_SERVICE_HOST": "10.0.0.1",
           "KUBERNETES_SERVICE_PORT": "443"}
    assert in_cluster_available(env, str(sa))
    uc = in_cluster_config(env, str(sa))
    assert uc.url == "https://10.0.0.1:443"
    assert uc.token == "pod-token"
    assert uc.ca_file == str(sa / "ca.crt")
    with pytest.raises(KubeconfigError, match="in-cluster"):
        in_cluster_config({}, str(sa))


def test_kubeconfig_relative_paths_resolve_against_file(tmp_path):
    (tmp_path / "ca.crt").write_text("CA")
    (tmp_path / "tok").write_text("file-token\n")
    path = write_kubeconfig(
        tmp_path,
        extra_cluster="    certificate-authority: ca.crt\n",
        extra_user="    tokenFile: tok\n")
    uc = load_kubeconfig(path)
    assert uc.ca_file == str(tmp_path / "ca.crt")
    # explicit token wins over tokenFile; drop it to exercise the file
    import yaml as _yaml

    doc = _yaml.safe_load(open(path))
    del doc["users"][0]["user"]["token"]
    (tmp_path / "kc2.yaml").write_text(_yaml.safe_dump(doc))
    uc = load_kubeconfig(str(tmp_path / "kc2.yaml"))
    assert uc.token == "file-token"


def test_options_kubeconfig_validation(tmp_path):
    base = dict(rule_content="x")
    with pytest.raises(OptionsError, match="mutually exclusive"):
        Options(upstream_url="http://u", kubeconfig="kc", **base).validate()
    with pytest.raises(OptionsError, match="requires kubeconfig"):
        Options(upstream_url="http://u", kubeconfig_context="c",
                **base).validate()
    with pytest.raises(OptionsError, match="upstream kube-apiserver"):
        Options(**base).validate()  # nothing given, not in-cluster
    # connection-override flags are rejected (not silently dropped) when
    # the upstream comes from a kubeconfig
    with pytest.raises(OptionsError, match="only apply with upstream-url"):
        Options(kubeconfig="kc", upstream_ca_file="ca.pem",
                **base).validate()
    with pytest.raises(OptionsError, match="only apply with upstream-url"):
        Options(kubeconfig="kc", upstream_insecure=True, **base).validate()


def test_proxy_through_kubeconfig_upstream(tmp_path):
    """End to end: the proxy dials the upstream resolved from a
    kubeconfig (server URL + bearer token), and the token actually
    reaches the upstream."""
    RULES = open(__import__("os").path.join(
        __import__("os").path.dirname(__file__), "..", "deploy",
        "rules.yaml")).read()
    BOOT = open(__import__("os").path.join(
        __import__("os").path.dirname(__file__), "..", "deploy",
        "bootstrap.yaml")).read()

    async def go():
        fake = FakeKube()
        seen = {}

        async def check_auth(req):
            seen["auth"] = next((v for k, v in req.headers.items()
                                 if k.lower() == "authorization"), None)
            return await fake(req)

        server, port = await serve_upstream(check_auth)
        kc = write_kubeconfig(tmp_path, server=f"http://127.0.0.1:{port}")
        cfg = Options(
            rule_content=RULES, bootstrap_content=BOOT,
            kubeconfig=kc,
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            bind_port=0,
        ).complete()
        await cfg.workflow.resume_pending()
        from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient

        alice = InMemoryClient(cfg.server.handle, user="alice")
        resp = await alice.post("/api/v1/namespaces", {
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "kc-ns"}})
        assert resp.status == 201, resp.body
        resp = await alice.get("/api/v1/namespaces")
        assert [o["metadata"]["name"]
                for o in json.loads(resp.body)["items"]] == ["kc-ns"]
        assert seen["auth"] == "Bearer sekrit-token"
        await cfg.workflow.shutdown()
        server.close()
    asyncio.run(go())
