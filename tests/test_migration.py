"""Live schema migration (ISSUE 19): the SchemaMigrator phase machine.

Covers the full contract stack:
- diff classification (additive / rewriting / incompatible-with-typed-
  refusal) and the refusal happening BEFORE any engine state changes;
- the journaled backfill + watch-echo suppression (exactly-once watch
  streams across the cut);
- decision-cache survival: unaffected keys keep their verdicts through
  the cutover, affected keys are surgically retired;
- the boot-time crash matrix driven from persisted record files;
- the wire surface (migrate_* ops over a loopback EngineServer);
- the acceptance run: a rewriting migration under sustained load with a
  SIGKILL mid-backfill and restart — completes on re-begin with zero
  acked-write loss and zero verdict flaps on unaffected permissions.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine
from spicedb_kubeapi_proxy_tpu.engine.store import (
    RelationshipFilter,
    StoreError,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.migration import recover, schema_digest
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.models.schema import (
    ADDITIVE,
    INCOMPATIBLE,
    REWRITING,
    IncompatibleSchemaChange,
    diff_schemas,
    ir_digest,
    require_compatible,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship

BASE = """
definition user {}
definition group {
  relation member: user
}
definition namespace {
  relation viewer: user | group#member
  permission view = viewer
}
definition pod {
  relation namespace: namespace
  relation viewer: user
  permission view = viewer + namespace->view
}
"""

# additive: pod grows an auditor relation + audit permission — nothing
# existing changes, no tuples rewritten
ADDITIVE_TARGET = BASE.replace(
    "  relation viewer: user\n",
    "  relation viewer: user\n  relation auditor: user\n").replace(
    "  permission view = viewer + namespace->view\n",
    "  permission view = viewer + namespace->view\n"
    "  permission audit = auditor\n")

# rewriting: a caveat attached to the LIVE pod#viewer relation — the
# allowed-subject set gains an entry, every stored viewer tuple is
# re-validated + backfilled. namespace#view stays outside the closure.
REWRITING_TARGET = ADDITIVE_TARGET.replace(
    "definition user {}",
    "caveat probation(level int) {\n  level < 3\n}\n\n"
    "definition user {}").replace(
    "  relation viewer: user\n  relation auditor: user\n",
    "  relation viewer: user | user with probation\n"
    "  relation auditor: user\n")

# incompatible: pod#viewer dropped while tuples may reference it
INCOMPATIBLE_TARGET = BASE.replace(
    "  relation viewer: user\n  permission view = viewer +"
    " namespace->view\n",
    "  permission view = namespace->view\n")


def _engine(schema_text: str = BASE) -> Engine:
    return Engine(schema=parse_schema(schema_text))


def _seed(e: Engine, n: int = 12) -> None:
    ops = [WriteOp("touch", Relationship(
        "pod", f"ns/p{i}", "viewer", "user", f"u{i}")) for i in range(n)]
    ops += [WriteOp("touch", Relationship(
        "namespace", "ns0", "viewer", "user", "owner"))]
    ops += [WriteOp("touch", Relationship(
        "pod", "ns/p0", "namespace", "namespace", "ns0"))]
    e.write_relationships(ops)


# ---------------------------------------------------------------------------
# diff classification
# ---------------------------------------------------------------------------


def test_diff_classifies_additive():
    d = diff_schemas(parse_schema(BASE), parse_schema(ADDITIVE_TARGET))
    assert d.classification == ADDITIVE
    assert not d.rewrite_relations
    # the untouched permission stays OUT of the affected closure
    assert not d.is_affected("namespace", "view")


def test_diff_classifies_rewriting_with_member_closure():
    d = diff_schemas(parse_schema(ADDITIVE_TARGET),
                     parse_schema(REWRITING_TARGET))
    assert d.classification == REWRITING
    assert ("pod", "viewer") in d.rewrite_relations
    # the closure pulls in dependents of the changed relation...
    assert d.is_affected("pod", "view")
    # ...but spares members whose walk never touches it
    assert not d.is_affected("namespace", "view")
    assert not d.is_affected("group", "member")


def test_diff_incompatible_typed_refusal_names_the_member():
    with pytest.raises(IncompatibleSchemaChange) as ei:
        require_compatible(parse_schema(BASE),
                           parse_schema(INCOMPATIBLE_TARGET))
    msg = str(ei.value)
    assert "pod" in msg and "viewer" in msg
    assert ei.value.reasons  # one line per blocking change


def test_ir_digest_order_independent():
    # same IR, permuted definitions + reformatted: identical digest
    blocks = [b for b in BASE.split("definition") if b.strip()]
    reordered = "definition" + "definition".join(reversed(blocks))
    assert ir_digest(parse_schema(BASE)) == ir_digest(
        parse_schema(reordered))
    assert ir_digest(parse_schema(BASE)) != ir_digest(
        parse_schema(ADDITIVE_TARGET))


# ---------------------------------------------------------------------------
# engine-level migrations
# ---------------------------------------------------------------------------


def test_additive_migration_end_to_end():
    e = _engine()
    _seed(e)
    item = CheckItem("pod", "ns/p3", "view", "user", "u3")
    assert e.check(item)
    st = e.begin_schema_migration(ADDITIVE_TARGET, wait=True)
    assert st["phase"] == "done"
    assert st["classification"] == "additive"
    assert st["backfilled"] == 0
    assert st["time_to_cut_ms"] is not None
    # untouched verdict survives; the NEW surface is immediately live
    assert e.check(item)
    e.write_relationships([WriteOp("touch", Relationship(
        "pod", "ns/p3", "auditor", "user", "aud"))])
    assert e.check(CheckItem("pod", "ns/p3", "audit",
                             "user", "aud"))


def test_rewriting_migration_backfills_and_keeps_watch_exactly_once():
    e = _engine(ADDITIVE_TARGET)
    _seed(e, n=9)
    before = e.watch_since(0)
    rev0 = e.revision
    item = CheckItem("pod", "ns/p1", "view", "user", "u1")
    ns_item = CheckItem("namespace", "ns0", "view", "user", "owner")
    assert e.check(item) and e.check(ns_item)
    st = e.begin_schema_migration(REWRITING_TARGET, wait=True, batch=4)
    assert st["phase"] == "done", st
    assert st["classification"] == "rewriting"
    assert st["backfilled"] == 9  # every stored pod#viewer tuple
    assert st["suppressed"] >= 3  # 9 rows at batch=4 -> 3 echo batches
    # exactly-once: the backfill echo revisions never reach watchers —
    # the stream after the migration equals the stream before it
    after = e.watch_since(0)
    assert [(ev.revision, ev.relationship) for ev in after] == \
        [(ev.revision, ev.relationship) for ev in before]
    assert all(ev.revision <= rev0 for ev in after)
    # verdicts on pre-existing (uncaveated) grants survive the cut, and
    # the new trait is live: a caveated viewer write is now accepted
    assert e.check(item) and e.check(ns_item)
    e.write_relationships([WriteOp("touch", Relationship(
        "pod", "ns/p1", "viewer", "user", "probie",
        caveat="probation", caveat_context='{"level": 1}'))])


def test_incompatible_refused_before_any_state_change():
    e = _engine()
    _seed(e)
    rev0 = e.revision
    schema0 = e.schema
    with pytest.raises(IncompatibleSchemaChange):
        e.begin_schema_migration(INCOMPATIBLE_TARGET)
    assert e.revision == rev0  # not a byte moved
    assert e.schema is schema0
    # the refused begin must not wedge the single-active slot
    st = e.begin_schema_migration(ADDITIVE_TARGET, wait=True)
    assert st["phase"] == "done"


def test_rewriting_refused_when_stored_tuple_invalid_under_target():
    e = _engine(ADDITIVE_TARGET)
    _seed(e, n=3)
    # S' REQUIRES the caveat on pod#viewer: stored uncaveated tuples
    # cannot re-validate, so the migration refuses up front
    required = ADDITIVE_TARGET.replace(
        "definition user {}",
        "caveat probation(level int) {\n  level < 3\n}\n\n"
        "definition user {}").replace(
        "  relation viewer: user\n  relation auditor: user\n",
        "  relation viewer: user with probation\n"
        "  relation auditor: user\n")
    rev0 = e.revision
    with pytest.raises(IncompatibleSchemaChange, match="does not validate"):
        e.begin_schema_migration(required)
    assert e.revision == rev0


def test_single_active_migration_and_coordinated_cut():
    e = _engine()
    _seed(e)
    e.begin_schema_migration(ADDITIVE_TARGET, hold_at_dual=True)
    deadline = time.monotonic() + 30
    while e.migration_status()["phase"] != "dual":
        assert time.monotonic() < deadline, e.migration_status()
        time.sleep(0.01)
    with pytest.raises(StoreError, match="already running"):
        e.begin_schema_migration(REWRITING_TARGET)
    st = e.cut_schema_migration(wait=True)
    assert st["phase"] == "done"
    # idempotent: a second cut just reports the terminal status
    assert e.cut_schema_migration(wait=True)["phase"] == "done"


def test_abort_before_cut_restores_nothing_because_nothing_changed():
    e = _engine()
    _seed(e)
    schema0 = e.schema
    e.begin_schema_migration(ADDITIVE_TARGET, hold_at_dual=True)
    deadline = time.monotonic() + 30
    while e.migration_status()["phase"] != "dual":
        assert time.monotonic() < deadline
        time.sleep(0.01)
    st = e.abort_schema_migration()
    assert st["phase"] == "aborted"
    assert e.schema is schema0
    # one-way past the cut: aborting a DONE migration refuses
    e.begin_schema_migration(ADDITIVE_TARGET, wait=True)
    with pytest.raises(StoreError, match="cannot abort"):
        e.abort_schema_migration()


def test_decision_cache_unaffected_keys_survive_the_cut():
    e = _engine(ADDITIVE_TARGET)
    e.enable_decision_cache()
    _seed(e, n=6)
    # warm verdicts on BOTH sides of the diff
    e.check(CheckItem("namespace", "ns0", "view", "user", "owner"))
    e.check(CheckItem("pod", "ns/p2", "view", "user", "u2"))

    def cached_pairs():
        pairs = set()
        for sh in e._decision_cache._shards:
            with sh.lock:
                for k in sh.entries:
                    if k[0] == "check":
                        pairs.add((k[2], k[4]))
        return pairs

    assert ("namespace", "view") in cached_pairs()
    assert ("pod", "view") in cached_pairs()
    st = e.begin_schema_migration(REWRITING_TARGET, wait=True)
    assert st["phase"] == "done"
    survivors = cached_pairs()
    # surgical retirement: the affected closure is gone, the rest stays
    assert ("namespace", "view") in survivors
    assert ("pod", "view") not in survivors
    assert ("pod", "viewer") not in survivors


# ---------------------------------------------------------------------------
# boot crash matrix (record files)
# ---------------------------------------------------------------------------


def _record(path, phase, to_text, suppressed=()):
    doc = {"phase": phase, "to_text": to_text,
           "to_digest": schema_digest(to_text),
           "suppressed_revisions": list(suppressed),
           "started": time.time(), "updated": time.time()}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return str(path)


@pytest.mark.parametrize("phase", ["planned", "compiling", "backfill",
                                   "dual"])
def test_recover_aborts_pre_cut_phases(tmp_path, phase):
    e = _engine()
    schema0 = e.schema
    path = _record(tmp_path / "migration.json", phase, ADDITIVE_TARGET,
                   suppressed=(7, 9))
    out = recover(e, path)
    assert out["action"] == "aborted" and out["phase"] == phase
    assert not os.path.exists(path)  # record cleared
    assert e.schema is schema0  # serving schema never moved
    # the echo revisions are in the replayed log: suppression re-armed
    assert {7, 9} <= set(e._watch_suppress)


def test_recover_resumes_persisted_cut(tmp_path):
    e = _engine()
    _seed(e, n=3)
    path = _record(tmp_path / "migration.json", "cut", ADDITIVE_TARGET)
    out = recover(e, path)
    assert out["action"] == "resumed" and out["phase"] == "cut"
    assert ir_digest(e.schema) == ir_digest(parse_schema(ADDITIVE_TARGET))
    # the record was promoted to the done marker (stale-flag rule)...
    with open(path, encoding="utf-8") as f:
        assert json.load(f)["phase"] == "done"
    # ...and a later boot whose bootstrap caught up clears it
    out2 = recover(e, path)
    assert out2["action"] == "cleared"
    assert not os.path.exists(path)


def test_recover_done_marker_reapplies_until_bootstrap_catches_up(
        tmp_path):
    e = _engine()  # boots with the STALE schema
    path = _record(tmp_path / "migration.json", "done", ADDITIVE_TARGET)
    out = recover(e, path)
    assert out["action"] == "resumed"
    assert ir_digest(e.schema) == ir_digest(parse_schema(ADDITIVE_TARGET))
    assert os.path.exists(path)  # marker outlives the boot


def test_recover_unreadable_record_fails_toward_booted_schema(tmp_path):
    e = _engine()
    schema0 = e.schema
    path = str(tmp_path / "migration.json")
    with open(path, "w") as f:
        f.write("{not json")
    out = recover(e, path)
    assert out["action"] == "aborted"
    assert e.schema is schema0
    assert os.path.exists(path + ".corrupt")


def test_recover_nothing_to_do():
    e = _engine()
    assert recover(e, None) is None
    assert recover(e, "/nonexistent/migration.json") is None


# ---------------------------------------------------------------------------
# wire surface + acceptance
# ---------------------------------------------------------------------------


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_wire_migrate_ops_loopback():
    import asyncio

    from spicedb_kubeapi_proxy_tpu.engine.engine import SchemaViolation
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        EngineServer,
        RemoteEngine,
    )

    e = _engine()
    _seed(e)
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    srv = EngineServer(e, port=0)
    port = asyncio.run_coroutine_threadsafe(srv.start(), loop).result(10)
    client = RemoteEngine("127.0.0.1", port)
    try:
        # incompatible refusal rides the typed "schema" error kind —
        # NOT "internal", which client retry policy would hammer
        with pytest.raises(SchemaViolation, match="incompatible"):
            client.migrate_begin(INCOMPATIBLE_TARGET)
        st = client.migrate_begin(ADDITIVE_TARGET, hold_at_dual=True)
        assert st["phase"] in ("planned", "compiling", "backfill", "dual")
        deadline = time.monotonic() + 30
        while client.migrate_status()["phase"] != "dual":
            assert time.monotonic() < deadline
            time.sleep(0.02)
        st = client.migrate_cut(wait=True)
        assert st["phase"] == "done"
        assert client.migrate_status()["phase"] == "done"
    finally:
        client.close()
        asyncio.run_coroutine_threadsafe(srv.stop(), loop).result(10)
        loop.call_soon_threadsafe(loop.stop)
        t.join(10)


_HOST_WORKER = r"""
import os, sys
port, data_dir, bootstrap, repo = sys.argv[1:5]
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, repo)
from spicedb_kubeapi_proxy_tpu.engine.remote import main
sys.exit(main([
    "--bootstrap", bootstrap,
    "--bind-port", port,
    "--engine-insecure",
    "--data-dir", data_dir, "--wal-fsync", "always",
]))
"""

_BOOT_YAML = """\
schema: |-
%s
relationships: ""
"""


def _boot_host(tmp_path, port):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "host_worker.py"
    script.write_text(_HOST_WORKER)
    boot = tmp_path / "bootstrap.yaml"
    boot.write_text(_BOOT_YAML % "\n".join(
        "  " + ln for ln in BASE.strip().splitlines()))
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("FAILPOINTS", None)
    return subprocess.Popen(
        [sys.executable, str(script), str(port), str(data), str(boot),
         repo], env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT)


def _wait_up(client, budget=60.0):
    deadline = time.monotonic() + budget
    last = None
    while time.monotonic() < deadline:
        try:
            _ = client.revision
            return
        except Exception as err:  # noqa: BLE001 - boot poll
            last = err
            time.sleep(0.2)
    raise RuntimeError(f"host never came up: {last}")


def _target_with_workflow_defs() -> str:
    # parse_bootstrap appends the workflow definitions to every booted
    # schema, so the migration target must carry them too or the diff
    # sees them as removed (incompatible)
    from spicedb_kubeapi_proxy_tpu.models.bootstrap import WORKFLOW_DEFS

    return "\n".join([REWRITING_TARGET.replace(
        "  relation auditor: user\n", "").replace(
        "  permission audit = auditor\n", "")]
        + list(WORKFLOW_DEFS.values()))


def test_acceptance_sigkill_mid_backfill_under_load(tmp_path):
    """The ISSUE 19 acceptance run: rewriting migration under sustained
    check/write load, SIGKILL mid-backfill, restart (boot crash matrix
    aborts the torn attempt), re-begin completes. Zero acked writes
    lost; the unaffected namespace#view verdict never flaps."""
    from spicedb_kubeapi_proxy_tpu.engine.remote import RemoteEngine

    port = _free_port()
    proc = _boot_host(tmp_path, port)
    client = RemoteEngine("127.0.0.1", port, timeout=15.0)
    target = _target_with_workflow_defs()
    acked: list[int] = []
    flaps: list[tuple] = []
    stop = threading.Event()
    try:
        _wait_up(client)
        # the affected population the backfill will chew through, plus
        # the unaffected anchor the no-flap probe rides on
        client.write_relationships(
            [WriteOp("touch", Relationship(
                "pod", f"ns/p{i}", "viewer", "user", f"u{i}"))
             for i in range(60)]
            + [WriteOp("touch", Relationship(
                "namespace", "ns0", "viewer", "user", "owner"))])
        probe = CheckItem("namespace", "ns0", "view", "user", "owner")
        want = client.check(probe)
        assert want is True

        def load():
            i = 1000
            while not stop.is_set():
                try:
                    client.write_relationships([WriteOp(
                        "touch", Relationship("pod", f"ns/p{i}", "viewer",
                                              "user", f"u{i}"))])
                    acked.append(i)
                    if client.check(probe) != want:
                        flaps.append(("during", i))
                except Exception:  # noqa: BLE001 - the kill window
                    pass
                i += 1
                time.sleep(0.01)

        lt = threading.Thread(target=load, daemon=True)
        lt.start()
        # paced backfill so the SIGKILL genuinely lands mid-backfill
        client.migrate_begin(target, batch=4, backfill_pause=0.2)
        deadline = time.monotonic() + 60
        while True:
            st = client.migrate_status()
            if st and st["phase"] == "backfill" and st["backfilled"] > 0:
                break
            assert time.monotonic() < deadline, st
            time.sleep(0.02)
        proc.kill()  # SIGKILL, mid-backfill by construction
        proc.wait(timeout=15)
        stop.set()
        lt.join(10)

        proc = _boot_host(tmp_path, port)
        _wait_up(client)
        # crash matrix: no cut persisted -> the boot aborted the torn
        # attempt and serves the OLD schema; probe verdict identical
        st = client.migrate_status()
        assert st is None or st["phase"] in ("aborted", "done")
        assert client.check(probe) == want
        # zero acked-write loss across the SIGKILL (wal-fsync=always)
        present = {r.resource_id for r in client.read_relationships(
            RelationshipFilter(resource_type="pod", relation="viewer"))}
        missing = [i for i in acked if f"ns/p{i}" not in present]
        assert not missing, f"acked writes lost: {missing[:10]}"

        # re-begin completes end-to-end on the recovered store
        st = client.migrate_begin(target, wait=True)
        assert st["phase"] == "done", st
        assert st["backfilled"] >= 60
        assert client.check(probe) == want
        assert not flaps
        # and the migrated surface is live: caveated write accepted
        client.write_relationships([WriteOp("touch", Relationship(
            "pod", "ns/p0", "viewer", "user", "probie",
            caveat="probation", caveat_context='{"level": 1}'))])
    finally:
        stop.set()
        client.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=15)
