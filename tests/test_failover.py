"""Leader failover for the mirrored engine: term fencing, deterministic
election, sync replication acks, client-side endpoint failover, /readyz
replication reporting — plus the end-to-end acceptance test (SIGKILL the
leader under concurrent writes; a follower promotes with a higher term,
no acked write lost under ``--wal-fsync always``, only fail-closed
errors during the window; a resurrected old leader demotes and
converges)."""

from __future__ import annotations

import asyncio
import json
import os
import queue
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.engine.remote import (
    EngineServer,
    FailoverEngine,
    NotLeaderError,
    RemoteEngine,
    _pack,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.parallel.failover import (
    FailoverError,
    choose_candidate,
    parse_peers,
)
from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
    MirroredEngine,
    StaleTermError,
    fence_term,
    follower_loop,
)
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics
from spicedb_kubeapi_proxy_tpu.utils.resilience import (
    DependencyUnavailable,
)

REJECTED = "mirror_frames_rejected_stale_term_total"


def rel(i, who="alice"):
    return parse_relationship(f"namespace:n{i}#creator@user:{who}")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- fencing ------------------------------------------------------------------


def test_fence_term_semantics():
    metrics.reset()
    # missing term (pre-term peer): no fencing, no adoption
    assert fence_term(None, 3) == 3
    # equal and higher terms pass (higher is adopted by the caller)
    assert fence_term(3, 3) == 3
    assert fence_term(5, 3) == 5
    assert metrics.counter(REJECTED).value == 0
    # a stale term is rejected AND counted
    with pytest.raises(StaleTermError):
        fence_term(2, 3)
    assert metrics.counter(REJECTED).value == 1


def test_split_brain_stale_frame_rejected_over_the_wire():
    """Deterministic split-brain: a follower that has adopted term 2
    receives a frame stamped term 1 (a deposed leader's late write).
    The frame must be REJECTED — observable via the metric — and must
    not touch the store."""
    metrics.reset()

    def fake_old_leader(port, ready):
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        ready.set()
        conn, _ = srv.accept()
        # consume the subscribe request frame
        hdr = conn.recv(4)
        (n,) = struct.unpack(">I", hdr)
        while n > 0:
            n -= len(conn.recv(n))
        # ack claiming term 2 (so the SUBSCRIPTION itself is accepted)...
        conn.sendall(_pack({"ok": True,
                            "result": {"subscribed": True, "term": 2}}))
        # ...then a write frame stamped with the DEPOSED term 1
        conn.sendall(_pack({"ok": True, "frame": {
            "seq": 1, "term": 1, "method": "write_relationships",
            "ops": [{"op": "touch", "rel": {
                "resource_type": "namespace", "resource_id": "ghost",
                "relation": "creator", "subject_type": "user",
                "subject_id": "mallory", "subject_relation": None,
                "expiration": None}}],
            "preconditions": []}}))
        time.sleep(2.0)  # hold the socket open while the client fences
        conn.close()
        srv.close()

    port = _free_port()
    ready = threading.Event()
    t = threading.Thread(target=fake_old_leader, args=(port, ready),
                         daemon=True)
    t.start()
    assert ready.wait(5)
    follower = Engine()
    with pytest.raises(StaleTermError):
        follower_loop(follower, "127.0.0.1", port, current_term=2,
                      heartbeat_timeout=5.0, fail_on_loss=True)
    assert metrics.counter(REJECTED).value >= 1
    assert follower.revision == 0, "a fenced frame must not apply"
    t.join(5)


def test_stale_subscription_ack_rejected():
    """A follower that already adopted term 5 must refuse to FOLLOW a
    leader still claiming term 3 (not just its frames)."""
    metrics.reset()

    def stale_leader(port, ready):
        srv = socket.socket()
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        ready.set()
        conn, _ = srv.accept()
        hdr = conn.recv(4)
        (n,) = struct.unpack(">I", hdr)
        while n > 0:
            n -= len(conn.recv(n))
        conn.sendall(_pack({"ok": True,
                            "result": {"subscribed": True, "term": 3}}))
        time.sleep(2.0)
        conn.close()
        srv.close()

    port = _free_port()
    ready = threading.Event()
    t = threading.Thread(target=stale_leader, args=(port, ready),
                         daemon=True)
    t.start()
    assert ready.wait(5)
    with pytest.raises(StaleTermError):
        follower_loop(Engine(), "127.0.0.1", port, current_term=5,
                      heartbeat_timeout=5.0, fail_on_loss=True)
    assert metrics.counter(REJECTED).value >= 1
    t.join(5)


def test_subscribe_with_catchup_deposed_term_forces_full_state():
    """The general fencing form of PR 3's 'follower ahead of leader'
    rule: a subscriber from a DEPOSED term whose revision runs past the
    promotion baseline gets a full state transfer even when an effects
    replay would normally satisfy it."""
    inner = Engine()
    for i in range(3):
        inner.write_relationships([WriteOp("touch", rel(i))])
    baseline = inner.revision
    leader = MirroredEngine(inner, term=4)
    assert leader.baseline_revision == baseline
    leader.write_relationships([WriteOp("touch", rel(7))])
    # same term, within history: cheap effects replay (no payload)
    q, meta, payload = leader.subscribe_with_catchup(
        baseline, subscriber_term=4)
    assert payload is None and "effects" in meta
    assert meta["term"] == 4
    leader.unsubscribe(q)
    # deposed term, revision past the baseline: forced full state
    q, meta, payload = leader.subscribe_with_catchup(
        baseline + 1, subscriber_term=3)
    assert payload is not None and meta.get("state")
    assert meta["term"] == 4
    leader.unsubscribe(q)
    # deposed term but still WITHIN shared history: effects replay is
    # sound (divergence can only exist past the promotion baseline)
    q, meta, payload = leader.subscribe_with_catchup(
        baseline, subscriber_term=3)
    assert payload is None and "effects" in meta
    leader.unsubscribe(q)


# -- election -----------------------------------------------------------------


def test_choose_candidate_term_then_revision_then_lowest_id():
    # highest revision wins within a term
    assert choose_candidate({0: {"revision": 5}, 1: {"revision": 9},
                             2: {"revision": 7}}) == 1
    # tie on revision -> lowest peer id
    assert choose_candidate({2: {"revision": 9}, 1: {"revision": 9},
                             0: {"revision": 3}}) == 1
    # TERM dominates revision: a deposed lineage's inflated revision
    # count (its fenced-off writes) must not outrank the canonical
    # newer-term candidate
    assert choose_candidate({
        0: {"term": 1, "revision": 100},   # old leader, unreplicated tail
        1: {"term": 2, "revision": 95},    # canonical promoted follower
    }) == 1
    assert choose_candidate({}) is None


def test_parse_peers():
    assert parse_peers("a:1,b:2") == [("a", 1), ("b", 2)]
    assert parse_peers("[::1]:50051") == [("::1", 50051)]
    for bad in ("", "a", "a:0", "a:notaport", "a:70000"):
        with pytest.raises(FailoverError):
            parse_peers(bad)


def test_term_persistence_round_trip(tmp_path):
    from spicedb_kubeapi_proxy_tpu.persistence import (
        load_term,
        store_term,
    )

    d = str(tmp_path / "data")
    assert load_term(d) == 0  # no dir, no file: term 0
    store_term(d, 7)
    assert load_term(d) == 7
    store_term(d, 9)
    assert load_term(d) == 9
    # garbage file fails safe to 0 rather than crashing boot
    with open(os.path.join(d, "term"), "w") as f:
        f.write("not-json")
    assert load_term(d) == 0


# -- sync replication ---------------------------------------------------------


def test_sync_replicated_write_waits_for_follower_ack():
    inner = Engine()
    m = MirroredEngine(inner, term=1, mirror_queries=False,
                       sync_replication=True, replication_timeout=30.0)
    q = m.subscribe()
    done = threading.Event()

    def writer():
        m.write_relationships([WriteOp("touch", rel(1))])
        done.set()

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    # the frame is published but unacked: the write must NOT return
    assert not done.wait(0.5)
    wire = q.get_nowait()
    assert isinstance(wire, bytes)  # the published frame reached the sub
    m.record_ack(q, 1, term=1)
    assert done.wait(5), "ack must release the writer"
    t.join(5)
    # acks from another term are a deposed subscription's stragglers
    t2 = threading.Thread(
        target=lambda: (m.write_relationships([WriteOp("touch", rel(2))]),
                        done.set()), daemon=True)
    done.clear()
    t2.start()
    assert not done.wait(0.3)
    m.record_ack(q, 2, term=99)  # wrong term: ignored
    assert not done.wait(0.3)
    m.unsubscribe(q)  # a dead follower stops being waited on
    assert done.wait(5)
    t2.join(5)


def test_sync_replication_timeout_drops_laggard():
    inner = Engine()
    m = MirroredEngine(inner, term=1, mirror_queries=False,
                       sync_replication=True, replication_timeout=0.3)
    q = m.subscribe()
    t0 = time.monotonic()
    m.write_relationships([WriteOp("touch", rel(1))])
    assert time.monotonic() - t0 >= 0.25
    # the laggard was dropped (and sent the close sentinel)
    with m._subs_lock:
        assert q not in m._subs
    q.get_nowait()  # the frame
    assert q.get_nowait() is None  # the drop sentinel


def test_catchup_join_credits_the_cut_for_sync_replication():
    """A follower joining via catch-up never acks the frames the cut
    already covers — the leader must credit them at subscribe time, or
    a write racing the join stalls its client a full replication
    timeout and then kicks the freshly joined follower."""
    inner = Engine()
    m = MirroredEngine(inner, term=1, mirror_queries=False,
                       sync_replication=True, replication_timeout=30.0)
    # seq advances with no subscribers (frames skipped entirely)
    m.write_relationships([WriteOp("touch", rel(1))])
    m.write_relationships([WriteOp("touch", rel(2))])
    assert m.mirror_seq == 2
    q, meta, payload = m.subscribe_with_catchup(0, subscriber_term=1)
    with m._subs_lock:
        assert m._join_cut[id(q)] == meta["seq"] == 2
    # a write AFTER the join still demands a real ack
    done = threading.Event()
    t = threading.Thread(
        target=lambda: (m.write_relationships([WriteOp("touch", rel(3))]),
                        done.set()), daemon=True)
    t.start()
    assert not done.wait(0.3)
    m.record_ack(q, 3, term=1)
    assert done.wait(5)
    t.join(5)
    m.unsubscribe(q)


def test_floored_write_racing_a_join_waits_for_the_cut_ack():
    """The cut is responsibility accounting, NOT durability: a
    min-sync-replicas write whose frame the catch-up cut covers is
    released only by the joiner's REAL post-catch-up ack (sent after
    the transfer is applied and journaled) — never by the cut record
    itself, which exists before the joiner holds any bytes."""
    from spicedb_kubeapi_proxy_tpu.engine.store import StoreError

    inner = Engine()
    m = MirroredEngine(inner, term=1, mirror_queries=False,
                       sync_replication=True, replication_timeout=2.0,
                       min_sync_replicas=1)
    # joiner registers; its cut covers everything published so far
    q = m.subscribe()
    done = threading.Event()
    outcome: list = []

    def write():
        try:
            m.write_relationships([WriteOp("touch", rel(1))])
            outcome.append("acked")
        except StoreError as e:
            outcome.append(e)
        done.set()

    # simulate the race: the write publishes seq 1 to the registered
    # queue, then the catch-up cut lands at seq 1 (covering it)
    t = threading.Thread(target=write, daemon=True)
    t.start()
    assert not done.wait(0.3), "floored write must not ack on the cut"
    with m._subs_lock:
        m._join_cut[id(q)] = m._seq  # the cut covers the frame...
        m._ack_cond.notify_all()
    assert not done.wait(0.5), "...but a cut is not a durable ack"
    m.record_ack(q, 1, term=1)  # the joiner journaled the catch-up
    assert done.wait(5)
    t.join(5)
    assert outcome == ["acked"]
    m.unsubscribe(q)


def test_min_sync_replicas_fails_writes_closed():
    """--min-sync-replicas: a leader below its durability floor refuses
    writes (an unreplicated ack would not survive failover) and resumes
    the moment a follower is back."""
    from spicedb_kubeapi_proxy_tpu.engine.store import StoreError

    inner = Engine()
    m = MirroredEngine(inner, term=1, mirror_queries=False,
                       sync_replication=True, replication_timeout=5.0,
                       min_sync_replicas=1)
    with pytest.raises(StoreError, match="min-sync-replicas"):
        m.write_relationships([WriteOp("touch", rel(1))])
    assert inner.revision == 0, "a refused write must not apply"
    # a follower that DIES mid-wait (unsubscribes without acking) must
    # not let the write slip through the floor via the no-laggards exit
    q0 = m.subscribe()
    errs: list = []
    done0 = threading.Event()

    def doomed():
        try:
            m.write_relationships([WriteOp("touch", rel(5))])
        except StoreError as e:
            errs.append(e)
        done0.set()

    t0 = threading.Thread(target=doomed, daemon=True)
    t0.start()
    assert not done0.wait(0.3)  # parked awaiting the ack
    m.unsubscribe(q0)  # connection died before acking
    assert done0.wait(5)
    t0.join(5)
    assert errs and "min-sync-replicas" in str(errs[0])
    q = m.subscribe()
    done = threading.Event()
    t = threading.Thread(
        target=lambda: (m.write_relationships([WriteOp("touch", rel(1))]),
                        done.set()), daemon=True)
    t.start()
    assert not done.wait(0.3)  # published, awaiting the replica's ack
    m.record_ack(q, m.mirror_seq, term=1)
    assert done.wait(5)
    t.join(5)
    # revision 2: the doomed write above APPLIED locally before its
    # floor error (outcome-unknown semantics, like a write whose
    # response connection died) — only the ack was withheld
    assert inner.revision == 2
    m.unsubscribe(q)


def test_equal_term_leader_conflict_resolves_deterministically():
    """Two leaders at the SAME term (a crashed promotion's persisted
    term reused by the next election): the lower peer id keeps the term
    and bumps past it; the higher id demotes with a forced full-state
    rejoin."""
    from spicedb_kubeapi_proxy_tpu.parallel.failover import (
        FailoverCoordinator,
        ROLE_FOLLOWER,
        ROLE_LEADER,
    )

    def coordinator(self_id):
        eng = Engine()
        srv = EngineServer(eng)  # never started: just the attr surface
        c = FailoverCoordinator(
            eng, srv, [("127.0.0.1", 1), ("127.0.0.1", 2)], self_id,
            heartbeat_interval=0.01, boot_grace=0.0)
        return c

    # winner side (peer 0): sees peer 1 leading at its own term
    c0 = coordinator(0)
    c0.term = 2
    c0._promote({})  # term -> 3, role leader
    assert c0.role == ROLE_LEADER and c0.term == 3
    probes = iter([
        {1: {"role": "leader", "term": 3, "revision": 0, "peer_id": 1}},
        {},  # conflict resolved: stop the lease loop
    ])

    def scripted_probe():
        try:
            return next(probes)
        except StopIteration:
            c0._stop.set()
            return {}

    c0._probe_all = scripted_probe
    c0._lead()
    assert c0.role == ROLE_LEADER
    assert c0.term == 4, "the winner must bump PAST the conflicted term"
    assert c0._mirrored.term == 4, "new frames must carry the bumped term"

    # loser side (peer 1): sees peer 0 leading at its own term
    c1 = coordinator(1)
    c1.term = 2
    c1._promote({})  # term -> 3
    c1._probe_all = lambda: {
        0: {"role": "leader", "term": 3, "revision": 0, "peer_id": 0}}
    c1._lead()
    assert c1.role == ROLE_FOLLOWER
    assert c1._rejoin_full, "the loser's term-3 history is suspect"
    # ...and the suspicion clears once it legitimately promotes again
    c1._promote({})
    assert not c1._rejoin_full


def test_demotion_closes_deposed_wrapper_streams():
    """A deposed leader's still-connected followers must SEE the
    demotion (stream close -> LeaderLost -> election), not keep eating
    its equal-term heartbeats forever."""
    m = MirroredEngine(Engine(), term=3, mirror_queries=False,
                       sync_replication=True)
    q1, q2 = m.subscribe(), m.subscribe()
    m.close_subscribers()
    assert q1.get_nowait() is None and q2.get_nowait() is None
    with m._subs_lock:
        assert not m._subs and not m._acked and not m._join_cut
    # plain subscribe() seeds responsibility, never durability
    q3 = m.subscribe()
    with m._subs_lock:
        assert m._acked[id(q3)] == 0
        assert m._join_cut[id(q3)] == m._seq


def test_failover_mode_skips_query_mirroring():
    inner = Engine()
    inner.write_relationships([WriteOp("touch", rel(1))])
    m = MirroredEngine(inner, term=1, mirror_queries=False,
                       sync_replication=True, replication_timeout=5.0)
    q = m.subscribe()
    # queries serve leader-locally: nothing published, nothing awaited
    assert m.check_bulk(
        [CheckItem("namespace", "n1", "view", "user", "alice")]) == [True]
    assert q.empty()
    m.unsubscribe(q)


# -- client-side failover -----------------------------------------------------


def _status(role, term, rev=0, pid=0):
    d = {"role": role, "term": term, "revision": rev, "peer_id": pid,
         "lag": 0}

    def fn():
        d["revision"] = d.get("revision", 0)
        return dict(d)

    fn.d = d
    return fn


def test_failover_engine_reresolves_and_fails_closed():
    metrics.reset()

    async def go():
        e_a, e_b = Engine(), Engine()
        st_a = _status("leader", 1, pid=0)
        st_b = _status("follower", 1, pid=1)
        srv_a = EngineServer(e_a, failover_status=st_a)
        srv_b = EngineServer(e_b, failover_status=st_b)
        port_a, port_b = await srv_a.start(), await srv_b.start()
        fe = FailoverEngine(
            [("127.0.0.1", port_a), ("127.0.0.1", port_b)],
            connect_timeout=1.0, timeout=5.0, retries=0,
            probe_timeout=2.0, resolve_deadline=5.0)
        w = [WriteOp("touch", rel(0))]
        assert await asyncio.to_thread(fe.write_relationships, w) == 1
        assert e_a.revision == 1 and e_b.revision == 0

        # a follower answers not_leader: rejected BEFORE dispatch, so
        # even a WRITE re-aims at the real leader transparently
        fe._primary_idx = 1
        w2 = [WriteOp("touch", rel(1))]
        assert await asyncio.to_thread(fe.write_relationships, w2) == 2
        assert e_a.revision == 2 and e_b.revision == 0
        assert metrics.counter("failover_total").value >= 1

        # the leader dies; B is promoted (term 2): a READ re-resolves
        await srv_a.stop()
        st_b.d.update(role="leader", term=2)
        e_b.write_relationships([WriteOp("touch", rel(9, "bob"))])
        got = await asyncio.to_thread(
            fe.check_bulk,
            [CheckItem("namespace", "n9", "view", "user", "bob")])
        assert got == [True]
        st = await asyncio.to_thread(fe.replication_status)
        assert st["role"] == "leader" and st["term"] == 2

        # an IDLE proxy (no data traffic since the failover) must still
        # recover via /readyz's replication_status — it re-resolves on
        # its own instead of reporting electing forever
        fe_idle = FailoverEngine(
            [("127.0.0.1", port_a), ("127.0.0.1", port_b)],
            connect_timeout=0.5, timeout=5.0, retries=0,
            probe_timeout=2.0, resolve_deadline=5.0)
        st = await asyncio.to_thread(fe_idle.replication_status)
        assert st["role"] == "leader" and st["term"] == 2
        fe_idle.close()

        # nobody leads: calls fail CLOSED with the 503-mapped family,
        # never a stale answer from the demoted follower
        st_b.d.update(role="electing")
        fe2 = FailoverEngine(
            [("127.0.0.1", port_a), ("127.0.0.1", port_b)],
            connect_timeout=0.5, timeout=2.0, retries=0,
            probe_timeout=1.0, resolve_deadline=1.0)
        with pytest.raises(DependencyUnavailable):
            await asyncio.to_thread(
                fe2.check_bulk,
                [CheckItem("namespace", "n9", "view", "user", "bob")])
        fe.close()
        fe2.close()
        await srv_b.stop()

    asyncio.run(go())


def test_role_gate_rejects_everything_but_failover_state():
    async def go():
        e = Engine()
        e.write_relationships([WriteOp("touch", rel(1))])
        st = _status("follower", 3, pid=1)
        srv = EngineServer(e, failover_status=st)
        port = await srv.start()
        remote = RemoteEngine("127.0.0.1", port, retries=0)
        # introspection always answers...
        info = await asyncio.to_thread(remote.failover_state)
        assert info["role"] == "follower" and info["term"] == 3
        # ...every data op fails closed, mapped to the 503 family
        with pytest.raises(NotLeaderError):
            await asyncio.to_thread(
                remote.check_bulk,
                [CheckItem("namespace", "n1", "view", "user", "alice")])
        with pytest.raises(NotLeaderError):
            await asyncio.to_thread(
                remote.write_relationships, [WriteOp("touch", rel(2))])
        assert e.revision == 1, "gated write must not dispatch"
        remote.close()
        await srv.stop()

    asyncio.run(go())


def test_options_parse_engine_endpoint_list(tmp_path):
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options,
        OptionsError,
    )

    o = Options(engine_endpoint="tcp://h1:50051,h2:50052,tcp://[::1]:7")
    assert o._parse_remote() == [("h1", 50051), ("h2", 50052), ("::1", 7)]
    with pytest.raises(OptionsError):
        Options(engine_endpoint="tcp://h1:50051,,bad")._parse_remote()
    with pytest.raises(OptionsError):
        Options(engine_endpoint="tcp://h1:50051,h2:0")._parse_remote()


def test_readyz_reports_replication_role(tmp_path):
    from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    RULES = open(os.path.join(os.path.dirname(__file__), "..", "deploy",
                              "rules.yaml")).read()
    from fake_kube import FakeKube

    async def go():
        e = Engine()
        srv = EngineServer(e)  # no coordinator: leader of itself
        port = await srv.start()
        cfg = Options(
            engine_endpoint=f"tcp://127.0.0.1:{port},127.0.0.1:1",
            engine_insecure=True,
            engine_connect_timeout=0.5,
            rule_content=RULES,
            upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
        ).complete()
        await cfg.workflow.resume_pending()
        alice = InMemoryClient(cfg.server.handle, user="alice")
        resp = await alice.get("/readyz")
        assert resp.status == 200
        assert b"[+]replication: role=leader" in resp.body
        # the whole set goes dark: /readyz gates traffic with the role
        await srv.stop()
        resp = await alice.get("/readyz")
        assert resp.status == 503
        assert b"[-]replication: " in resp.body
        assert b"role=electing" in resp.body
        await cfg.workflow.shutdown()
        cfg.engine.close()

    asyncio.run(go())


# -- the end-to-end acceptance test ------------------------------------------


FAILOVER_WORKER = r"""
import os, sys
peer_id, port0, port1, data_dir, repo = (sys.argv[1], sys.argv[2],
                                         sys.argv[3], sys.argv[4],
                                         sys.argv[5])
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, repo)
import jax
jax.config.update("jax_platforms", "cpu")
from spicedb_kubeapi_proxy_tpu.engine.remote import main

print("PEER %s STARTING" % peer_id, flush=True)
sys.exit(main([
    "--peers", "127.0.0.1:%s,127.0.0.1:%s" % (port0, port1),
    "--peer-id", peer_id,
    "--bind-port", port0 if peer_id == "0" else port1,
    "--token", "fo-tok", "--engine-insecure",
    "--data-dir", data_dir, "--wal-fsync", "always",
    "--mirror-heartbeat-seconds", "0.3",
    "--failover-boot-grace", "30",
]))
"""


def test_leader_sigkill_promotes_follower_no_acked_write_lost(tmp_path):
    """The acceptance pin: SIGKILL the leader under concurrent writes.
    (a) a follower promotes and serves with a HIGHER term within the
    heartbeat-timeout budget, (b) every write the old leader acked is
    present after promotion (sync replication + fsync always), (c) the
    resurrected old leader demotes to follower and converges; during
    the window requests fail CLOSED (the 503-mapped family), never
    answer wrong."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = str(tmp_path / "fo_worker.py")
    with open(script, "w") as f:
        f.write(FAILOVER_WORKER)
    port0, port1 = _free_port(), _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)

    def boot(peer_id):
        return subprocess.Popen(
            [sys.executable, script, str(peer_id), str(port0), str(port1),
             str(tmp_path / f"data{peer_id}"), repo_root],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo_root)

    def state_of(port, timeout=2.0):
        probe = RemoteEngine("127.0.0.1", port, token="fo-tok",
                             timeout=timeout, connect_timeout=timeout,
                             retries=0)
        try:
            return probe.failover_state()
        finally:
            probe.close()

    def wait_for_leader(budget=120.0, procs=()):
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            for p in procs:
                assert p.poll() is None, p.communicate()[0][-3000:]
            for port in (port0, port1):
                try:
                    st = state_of(port)
                except Exception:
                    continue
                if st["role"] == "leader":
                    return port, st
            time.sleep(0.3)
        raise AssertionError("no leader elected in time")

    procs = {0: boot(0), 1: boot(1)}
    client = None
    try:
        leader_port, st0 = wait_for_leader(procs=list(procs.values()))
        term0 = st0["term"]
        client = FailoverEngine(
            [("127.0.0.1", port0), ("127.0.0.1", port1)], token="fo-tok",
            connect_timeout=2.0, timeout=20.0, retries=0,
            probe_timeout=2.0, resolve_deadline=45.0)

        acked: list[int] = []
        window_errors: list[BaseException] = []
        stop_writes = threading.Event()

        def writer():
            i = 0
            while not stop_writes.is_set():
                try:
                    client.write_relationships(
                        [WriteOp("touch", rel(i, "writer"))])
                    acked.append(i)
                except (DependencyUnavailable, OSError) as e:
                    window_errors.append(e)  # fail-closed family: fine
                i += 1
                time.sleep(0.05)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        # let a batch of writes get acked through the original leader
        deadline = time.monotonic() + 30
        while len(acked) < 10 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert len(acked) >= 10, "no write traffic before the kill"

        # SIGKILL the leader mid-stream
        victim = 0 if leader_port == port0 else 1
        survivor_port = port1 if victim == 0 else port0
        t_kill = time.monotonic()
        procs[victim].kill()
        procs[victim].wait(timeout=10)

        # a follower must promote and serve: the writer thread's acked
        # list advancing past the kill proves end-to-end recovery
        acked_at_kill = len(acked)
        deadline = time.monotonic() + 45
        while len(acked) <= acked_at_kill \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        t_ready = time.monotonic() - t_kill
        stop_writes.set()
        t.join(30)
        assert len(acked) > acked_at_kill, \
            f"writes never resumed after failover ({window_errors[-3:]})"
        st1 = state_of(survivor_port)
        assert st1["role"] == "leader"
        assert st1["term"] > term0, "promotion must bump the term"
        # the budget: heartbeat loss detection (~1s at 0.3s cadence) +
        # election + promotion, with generous CI slack
        assert t_ready < 45, f"failover took {t_ready:.1f}s"

        # (b) EVERY acked write is present after promotion
        items = [CheckItem("namespace", f"n{i}", "view", "user", "writer")
                 for i in acked]
        verdicts = client.check_bulk(items)
        missing = [i for i, ok in zip(acked, verdicts) if not ok]
        assert not missing, f"acked writes lost in failover: {missing}"

        # (c) resurrect the old leader: it must DEMOTE and converge
        procs[victim] = boot(victim)
        victim_port = port0 if victim == 0 else port1
        deadline = time.monotonic() + 120
        converged = False
        while time.monotonic() < deadline:
            assert procs[victim].poll() is None, \
                procs[victim].communicate()[0][-3000:]
            try:
                st_old = state_of(victim_port)
                st_new = state_of(survivor_port)
            except Exception:
                time.sleep(0.5)
                continue
            if (st_old["role"] == "follower"
                    and st_old["term"] == st_new["term"]
                    and st_old["revision"] == st_new["revision"]):
                converged = True
                break
            time.sleep(0.5)
        assert converged, "old leader never converged as a follower"
        # and replication through the rejoined pair still works: this
        # write is sync-acked by the demoted old leader
        client.write_relationships([WriteOp("touch", rel(999, "writer"))])
        st_old = state_of(victim_port)
        st_new = state_of(survivor_port)
        assert st_old["revision"] == st_new["revision"]
    finally:
        if client is not None:
            client.close()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 20
        outs = []
        for p in procs.values():
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
            outs.append(p.communicate()[0])
    for out in outs:
        assert "STARTING" in out, out[-1500:]
