"""Relationship-store snapshots: save/load round-trips, resumed writes,
and the watch re-list contract (the graph analog of the reference's
durable state, SURVEY.md §5 checkpoint/resume)."""

import numpy as np
import pytest

from spicedb_kubeapi_proxy_tpu.engine import (
    CheckItem,
    Engine,
    RelationshipFilter,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.engine.store import StoreError
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship

SCHEMA = parse_schema("""
use expiration

definition user {}
definition group { relation member: user }
definition ns {
  relation viewer: user | group#member | user with expiration
  relation banned: user
  permission view = viewer - banned
}
""")


def build():
    e = Engine(schema=SCHEMA)
    e.write_relationships([WriteOp("touch", parse_relationship(r)) for r in (
        "group:eng#member@user:alice",
        "ns:dev#viewer@group:eng#member",
        "ns:dev#viewer@user:bob",
        "ns:dev#banned@user:bob",
        "ns:prod#viewer@user:carol[expiration:2124-01-01T00:00:00Z]",
        "ns:tmp#viewer@user:dave",
    )])
    # a deleted row must not resurrect through a snapshot
    e.delete_relationships(RelationshipFilter(resource_id="tmp"))
    return e


def checks(e):
    return [e.check(CheckItem("ns", n, "view", "user", u))
            for n, u in (("dev", "alice"), ("dev", "bob"), ("prod", "carol"),
                         ("tmp", "dave"), ("dev", "nobody"))]


def test_snapshot_round_trip(tmp_path):
    e = build()
    want = checks(e)
    assert want == [True, False, True, False, False]
    path = str(tmp_path / "graph.npz")
    e.save_snapshot(path)

    e2 = Engine(schema=SCHEMA)
    e2.load_snapshot(path)
    assert e2.revision == e.revision
    assert checks(e2) == want
    # full relationship fidelity incl. expiration timestamps
    orig = sorted(str(r) for r in e.read_relationships(RelationshipFilter()))
    back = sorted(str(r) for r in e2.read_relationships(RelationshipFilter()))
    assert back == orig


def test_snapshot_resumed_writes_and_interning(tmp_path):
    e = build()
    path = str(tmp_path / "graph.npz")
    e.save_snapshot(path)
    e2 = Engine(schema=SCHEMA)
    e2.load_snapshot(path)
    # new writes intern on top of restored tables: old + new ids coexist
    e2.write_relationships([WriteOp("touch", parse_relationship(
        "ns:dev#viewer@user:erin"))])
    assert e2.check(CheckItem("ns", "dev", "view", "user", "erin"))
    assert e2.check(CheckItem("ns", "dev", "view", "user", "alice"))
    # touch-delete of a restored row works (index rebuilt over loaded chunk)
    e2.delete_relationships(RelationshipFilter(subject_id="alice"))
    assert not e2.check(CheckItem("ns", "dev", "view", "user", "alice"))


def test_snapshot_watch_relist_contract(tmp_path):
    e = build()
    rev = e.revision
    path = str(tmp_path / "graph.npz")
    e.save_snapshot(path)
    e2 = Engine(schema=SCHEMA)
    e2.load_snapshot(path)
    # watching from the restored revision works (empty); from before it
    # demands a re-list, kube "resourceVersion too old" semantics
    assert e2.watch_since(rev) == []
    with pytest.raises(StoreError, match="re-list"):
        e2.watch_since(rev - 2)


def test_snapshot_round_trip_with_closured_block(tmp_path, monkeypatch):
    """Save/load with a closured self-pair block: the restored engine
    re-closes at compile, incremental membership deletes still take the
    O(block) re-close path, and results stay ground-truth exact."""
    import spicedb_kubeapi_proxy_tpu.ops.reachability as R
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 1)
    schema = parse_schema("""
definition user {}
definition group { relation member: user | group#member }
definition namespace {
  relation viewer: group#member
  permission view = viewer
}
""")
    e = Engine(schema=schema)
    e.write_relationships([WriteOp("touch", parse_relationship(r)) for r in (
        "group:leaf#member@user:alice",
        "group:mid#member@group:leaf#member",
        "group:root#member@group:mid#member",
        "namespace:ns#viewer@group:root#member",
    )])
    assert any(b.closured for b in e.compiled().blocks)
    path = str(tmp_path / "closured.npz")
    e.save_snapshot(path)

    e2 = Engine(schema=schema)
    e2.load_snapshot(path)
    cg2 = e2.compiled()
    assert any(b.closured for b in cg2.blocks), "closure survives restore"
    item = CheckItem("namespace", "ns", "view", "user", "alice")
    assert e2.check(item)
    # incremental delete on the restored engine stays O(block)
    compiles = metrics.counter("engine_graph_compiles_total").value
    e2.write_relationships([WriteOp("delete", parse_relationship(
        "group:mid#member@group:leaf#member"))])
    assert not e2.check(item)
    assert metrics.counter("engine_graph_compiles_total").value == compiles
    # re-add across the snapshot boundary: chain re-forms
    e2.write_relationships([WriteOp("touch", parse_relationship(
        "group:mid#member@group:leaf#member"))])
    assert e2.check(item)


def test_snapshot_atomic_overwrite(tmp_path):
    e = build()
    path = str(tmp_path / "graph.npz")
    e.save_snapshot(path)
    e.write_relationships([WriteOp("touch", parse_relationship(
        "ns:dev#viewer@user:frank"))])
    e.save_snapshot(path)  # overwrite in place
    e2 = Engine(schema=SCHEMA)
    e2.load_snapshot(path)
    assert e2.check(CheckItem("ns", "dev", "view", "user", "frank"))
