"""Tiered graph storage: HBM-hot / host-cold arenas (ISSUE 18).

Covers the acceptance surface:

- oracle parity with every block cold (budget so small nothing admits:
  each dispatch streams its demanded blocks in and answers match the
  all-resident engine exactly);
- demand closure: definitions never touched by traffic contribute ZERO
  device-resident bytes — their blocks record no accesses, never get
  admitted, and the ``engine_tier_hot_bytes`` gauge accounts only for
  the admitted working set;
- the randomized churn differential: interleaved promote / demote /
  stream-in with incremental appends AND deletes riding the overlay,
  oracle parity after every step, and ZERO recompiles during steady
  streaming (residency must never leak into the jit key —
  ``reachability._TRACE_BUILDS`` is the witness);
- beyond-budget cold start: a fresh engine under a 1-byte budget
  answers with parity and a non-empty
  ``engine_tier_miss_stall_seconds`` histogram;
- the TierStore placement mechanics (budget headroom, colder-victim
  eviction, pinned blocks never evicted, recency decay);
- the arena codec: directory-of-.npy save/load with a REAL mmap (npz
  cannot memory-map — np.load silently ignores mmap_mode for zips),
  and the Store.save_dir / load(mmap=True) snapshot round-trip.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import spicedb_kubeapi_proxy_tpu.ops.reachability as R  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine import Engine  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine.engine import CheckItem  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine.store import Store, WriteOp  # noqa: E402
from spicedb_kubeapi_proxy_tpu.models import parse_schema  # noqa: E402
from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship  # noqa: E402
from spicedb_kubeapi_proxy_tpu.persistence import codec  # noqa: E402
from spicedb_kubeapi_proxy_tpu.storage import ColdArena, TierStore  # noqa: E402
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics  # noqa: E402

SCHEMA = """
definition user {}

definition ns {
  relation viewer: user
  permission view = viewer
}

definition pod {
  relation viewer: user
  relation owner: ns
  permission view = viewer + owner->view
}

definition secret {
  relation viewer: user
  permission view = viewer
}
"""


def _build(budget=None, n=40):
    """An engine over a 4-definition graph: pod.view traffic exercises
    pod + ns blocks; the secret blocks exist (same size class) but no
    test query ever demands them."""
    e = Engine(schema=parse_schema(SCHEMA),
               device_graph_budget_bytes=budget)
    ops = []
    for i in range(n):
        ops.append(WriteOp("touch", Relationship(
            "pod", f"p{i}", "viewer", "user", f"u{i % 7}")))
        ops.append(WriteOp("touch", Relationship(
            "secret", f"s{i}", "viewer", "user", f"u{i % 5}")))
        ops.append(WriteOp("touch", Relationship(
            "pod", f"p{i}", "owner", "ns", f"n{i % 3}")))
    for j in range(3):
        ops.append(WriteOp("touch", Relationship(
            "ns", f"n{j}", "viewer", "user", "admin")))
    e.write_relationships(ops)
    return e


def _queries(n=40):
    return [CheckItem("pod", f"p{i}", "view", "user", u)
            for i in range(n) for u in ("u0", "u3", "admin")]


def _stalls():
    snap = metrics.hist_snapshot("engine_tier_miss_stall_seconds")
    return int(sum(snap["counts"])) if snap else 0


def test_all_cold_parity(monkeypatch):
    """Budget=1: nothing ever admits, every dispatch streams its demand
    set — answers must match the all-resident engine on every query."""
    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 4)
    base = _build()
    tiered = _build(budget=1)
    s0 = _stalls()
    for q in _queries():
        assert bool(base.check(q)) == bool(tiered.check(q)), q
    cg = tiered._compiled
    assert cg.tier is not None
    st = cg.tier.stats()
    assert st["hot_blocks"] == 0, "1-byte budget admitted a block"
    assert _stalls() > s0, "streaming never recorded a miss stall"


def test_untouched_definitions_zero_device_bytes(monkeypatch):
    """Demand closure: secret/ns-only blocks that pod traffic cannot
    reach record zero accesses, never become resident, and contribute
    zero bytes to the hot gauge."""
    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 4)
    e = _build()
    for q in _queries():
        e.check(q)
    cg = e._compiled
    tier = cg.enable_tiering(budget_bytes=1 << 40)  # everything COULD fit
    for q in _queries():
        e.check(q)
    st = tier.stats()
    untouched = [i for i, a in st["accesses"].items() if a == 0]
    assert untouched, "expected at least one undemanded block " \
                      "(the secret definition)"
    for i in untouched:
        assert not tier.entry_resident(i), \
            f"block {i} resident despite zero accesses"
    touched_bytes = sum(
        tier._entries[i].nbytes for i, a in st["accesses"].items()
        if a > 0 and tier.entry_resident(i))
    tier.publish_gauges()
    assert metrics.gauge("engine_tier_hot_bytes").value == touched_bytes
    assert st["hot_bytes"] < st["hot_bytes"] + st["cold_bytes"], \
        "untouched blocks must stay in the cold tier"


def test_churn_differential_promote_demote_stream(monkeypatch):
    """Randomized churn: overlay appends + deletes interleaved with
    explicit demotes (stream-in on the next query) and placement
    sweeps. Oracle parity after EVERY step, and zero recompiles once
    the fixed query shapes are warm."""
    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 4)
    rng = np.random.default_rng(42)
    base = _build()
    tiered = _build(budget=1 << 40)
    probes = [CheckItem("pod", "p1", "view", "user", "admin"),
              CheckItem("pod", "p3", "view", "user", "u3"),
              CheckItem("pod", "cx0", "view", "user", "u0")]
    for q in probes:  # warm both engines: traces + streamed admits
        base.check(q)
        tiered.check(q)
    cg = tiered._compiled
    tier = cg.tier
    builds0 = R._TRACE_BUILDS
    live = set()
    for step in range(24):
        op = rng.integers(3)
        if op == 0 or not live:
            rid = f"cx{int(rng.integers(4))}"
            w = WriteOp("touch", Relationship(
                "pod", rid, "viewer", "user", "u0"))
            live.add(rid)
        elif op == 1:
            rid = live.pop()
            w = WriteOp("delete", Relationship(
                "pod", rid, "viewer", "user", "u0"))
        else:
            w = WriteOp("touch", Relationship(
                "secret", f"sx{int(rng.integers(4))}", "viewer",
                "user", "u1"))
        base.write_relationships([w])
        tiered.write_relationships([w])
        if step % 5 == 4:
            # demote a resident block: the next dispatch that demands
            # it must stream it back, not re-trace
            resident = [i for i in range(len(tiered._compiled.blocks))
                        if tier.entry_resident(i)]
            if resident:
                tier.demote(int(rng.choice(resident)))
        if step % 7 == 6:
            R.tier_maintain(tiered._compiled)
        for q in probes:
            assert bool(base.check(q)) == bool(tiered.check(q)), \
                (step, q)
    assert R._TRACE_BUILDS == builds0, \
        "steady-state churn/streaming re-traced the fixpoint"


def test_beyond_budget_cold_start_parity_and_stalls(monkeypatch):
    """A fresh engine whose graph exceeds the budget from the first
    query: the cold start must stream, answer with oracle parity, and
    leave a non-empty miss-stall histogram."""
    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 4)
    oracle = _build()
    want = [bool(oracle.check(q)) for q in _queries()]
    s0 = _stalls()
    cold = _build(budget=1)
    got = [bool(cold.check(q)) for q in _queries()]
    assert got == want
    assert _stalls() > s0
    assert metrics.counter("engine_tier_misses_total").value > 0


def test_tier_store_placement_mechanics():
    """Unit coverage for the placement engine: headroom admission,
    colder-victim eviction, pinned immunity, recency decay."""
    tier = TierStore(budget_bytes=1000, arena=ColdArena())
    for i, nb in enumerate((400, 400, 400)):
        tier.register(i, nb, level=0)
    payload = ("A", None)
    assert tier.admit(0, payload)
    assert tier.admit(1, payload)
    # 3rd block would exceed budget*headroom (850); blocks 0/1 are
    # equally recent, so nothing strictly colder exists -> transient
    assert not tier.admit(2, payload)
    # heat 1, decay, then DEMAND 2 (lookup bumps its recency, as the
    # dispatch path does before admitting): now 0 is strictly colder
    # than 2 and a valid victim
    tier.lookup((1,))
    tier.place()
    tier.lookup((2,))
    assert tier.admit(2, payload)
    assert not tier.entry_resident(0)
    # pinned blocks always stick and never evict
    tier.pin(1)
    assert tier.admit(1, payload, pinned=True)
    tier.demand_cache_put(("k",), (0, 1))
    assert tier.demand_cache_get(("k",)) == (0, 1)
    tier.close()


def test_cold_arena_memory_and_spill(tmp_path):
    """Both arena forms round-trip; the spill form hands back REAL
    memory maps (directory-of-.npy — npz cannot mmap)."""
    cols = {"dst_local": np.arange(5, dtype=np.int32),
            "src_local": np.arange(5, 0, -1, dtype=np.int32)}
    mem = ColdArena()
    mem.put(7, cols)
    out = mem.get(7)
    np.testing.assert_array_equal(out["dst_local"], cols["dst_local"])
    assert mem.nbytes > 0
    mem.drop(7)
    assert not mem.has(7)

    spill = ColdArena(spill_dir=str(tmp_path))
    spill.put(3, cols)
    out = spill.get(3)
    np.testing.assert_array_equal(out["src_local"], cols["src_local"])
    assert isinstance(out["src_local"], np.memmap)


def test_codec_dir_save_load_mmap(tmp_path):
    """codec.save/load: atomic per-column .npy files; mmap=True returns
    lazily-paged memmaps with identical contents."""
    arrays = {"a": np.arange(100, dtype=np.int32),
              "b": (np.arange(50) % 2).astype(np.uint8)}
    path = str(tmp_path / "arena")
    n = codec.save(path, arrays)
    assert n == sum(a.nbytes for a in arrays.values())
    eager = codec.load(path)
    lazy = codec.load(path, mmap=True)
    for k in arrays:
        np.testing.assert_array_equal(eager[k], arrays[k])
        np.testing.assert_array_equal(lazy[k], arrays[k])
        assert isinstance(lazy[k], np.memmap)
        assert not isinstance(eager[k], np.memmap)


def test_store_save_dir_mmap_recovery(tmp_path, monkeypatch):
    """Snapshot recovery without the transient double-RAM copy: the
    directory snapshot loads mmap-backed and the recovered engine
    answers exactly like the original."""
    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 4)
    e = _build()
    want = [bool(e.check(q)) for q in _queries()]
    path = str(tmp_path / "snap")
    n = e.store.save_dir(path)
    assert n > 0 and os.path.isdir(path)

    e2 = Engine(schema=parse_schema(SCHEMA))
    e2.store.load(path, mmap=True)
    got = [bool(e2.check(q)) for q in _queries()]
    assert got == want

    # the raw Store round-trips through mmap too
    s = Store()
    s.load(path, mmap=True)
    assert s.revision == e.store.revision
