"""Rule parse/compile/eval matrix — modeled on the reference's broad unit
suites (rules_test.go TestParseRelString/TestCompile/TestCELConditions/
TestMapMatcherMatch and proxyrule rule_test.go TestRuleParsing/
TestValidation, SURVEY.md §4)."""

import pytest

from spicedb_kubeapi_proxy_tpu.rules.compile import (
    CompileError,
    compile_rule,
)
from spicedb_kubeapi_proxy_tpu.rules.expr import ExprError
from spicedb_kubeapi_proxy_tpu.rules.input import ResolveInput, UserInfo
from spicedb_kubeapi_proxy_tpu.rules.matcher import MapMatcher, RequestMeta
from spicedb_kubeapi_proxy_tpu.rules.proxyrule import (
    RuleValidationError,
    parse_rule_configs,
)
from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import parse_request_info


def _input(verb="create", resource="namespaces", name="dev", ns="",
           user="alice", groups=(), body=None):
    import json as _json

    path = f"/api/v1/{resource}" if not ns else \
        f"/api/v1/namespaces/{ns}/{resource}"
    if verb in ("get", "delete", "update", "patch"):
        path += f"/{name}"
    info = parse_request_info(
        "POST" if verb == "create" else "GET", path, {})
    info.verb = verb
    if body is None and verb == "create":
        # creates resolve the name from the object body, like the reference
        meta = {"name": name}
        if ns:
            meta["namespace"] = ns
        body = {"metadata": meta}
    return ResolveInput.create(
        info, UserInfo(name=user, groups=list(groups)),
        body=(_json.dumps(body).encode() if body is not None else None),
        headers={})


def _rule(yaml_text):
    return compile_rule(parse_rule_configs(yaml_text)[0])


# -- rel-string template parsing (TestParseRelString shape) ------------------

REL_OK = [
    # literal fields
    ("ns:dev#viewer@user:alice", ("ns", "dev", "viewer", "user", "alice", "")),
    # userset subject
    ("ns:dev#viewer@group:eng#member",
     ("ns", "dev", "viewer", "group", "eng", "member")),
    # templates in every position
    ("ns:{{name}}#viewer@user:{{user.name}}",
     ("ns", "dev", "viewer", "user", "alice", "")),
    # slash-joined namespaced name
    ("pod:{{namespacedName}}#creator@user:{{user.name}}",
     None),  # checked separately below
]


@pytest.mark.parametrize("tpl,want", REL_OK[:3])
def test_rel_template_positions(tpl, want):
    rule = _rule(f"""
match: [{{apiVersion: v1, resource: namespaces, verbs: [create]}}]
check: [{{tpl: "{tpl}"}}]
""")
    got = rule.checks[0].generate(_input())[0]
    assert (got.resource_type, got.resource_id, got.resource_relation,
            got.subject_type, got.subject_id, got.subject_relation) == want


def test_rel_template_namespaced_name():
    rule = _rule("""
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
check: [{tpl: "pod:{{namespacedName}}#creator@user:{{user.name}}"}]
""")
    got = rule.checks[0].generate(
        _input(resource="pods", ns="team-a", name="api",
               body={"metadata": {"name": "api", "namespace": "team-a"}}))[0]
    assert got.resource_id == "team-a/api"


@pytest.mark.parametrize("bad", [
    "ns:dev#viewer",          # no subject
    "ns:dev@user:alice",      # no relation
    "#viewer@user:alice",     # no resource
    "ns:dev#viewer@user:alice#a#b",  # double subject relation
])
def test_rel_template_malformed(bad):
    with pytest.raises((CompileError, RuleValidationError)):
        _rule(f"""
match: [{{apiVersion: v1, resource: namespaces, verbs: [create]}}]
check: [{{tpl: "{bad}"}}]
""")


def test_literal_fields_allow_kube_identifier_charsets():
    # service-account subjects carry ':'; label-derived relations carry
    # '.'/'/'; both must flow through literal-field validation (review
    # regression: the structural check must reject only '#'/'@' leaks)
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_rel_fields
    f = parse_rel_fields(
        "ns:x#admin@user:system:serviceaccount:default:builder")
    assert f["subject_id"] == "system:serviceaccount:default:builder"
    f = parse_rel_fields("pod:t/api#label-app.kubernetes.io/name@user:a")
    assert f["relation"] == "label-app.kubernetes.io/name"


def test_empty_resolved_field_is_an_error():
    rule = _rule("""
match: [{apiVersion: v1, resource: namespaces, verbs: [create]}]
check: [{tpl: "ns:{{object.metadata.labels.missing}}#v@user:{{user.name}}"}]
""")
    with pytest.raises(ExprError, match="empty|null"):
        rule.checks[0].generate(_input(body={"metadata": {"name": "dev"}}))


# -- validation matrix (rule_test.go TestValidation shape) -------------------

@pytest.mark.parametrize("doc,msg", [
    ("match: []\ncheck: [{tpl: 'a:b#c@d:e'}]", "match is required"),
    ("match: [{apiVersion: v1, resource: r}]", "needs verbs"),
    ("match: [{apiVersion: v1, verbs: [get]}]", "needs apiVersion and resource"),
    ("match: [{apiVersion: v1, resource: r, verbs: [frobnicate]}]",
     "invalid verb"),
    ("match: [{apiVersion: v1, resource: r, verbs: [get]}]\n"
     "check: [{tpl: 'a:b#c@d:e', tupleSet: 'x'}]", "mutually exclusive"),
    ("match: [{apiVersion: v1, resource: r, verbs: [get]}]\ncheck: [{}]",
     "is required"),
    ("match: [{apiVersion: v1, resource: r, verbs: [get]}]\n"
     "lock: Sometimes", "invalid lock mode"),
    ("match: [{apiVersion: v1, resource: r, verbs: [list]}]\n"
     "postcheck: [{tpl: 'a:b#c@d:e'}]", "incompatible with verbs"),
    ("match: [{apiVersion: v1, resource: r, verbs: [get]}]\n"
     "prefilter: [{lookupMatchingResources: {tpl: 'a:$#c@d:e'}}]",
     "fromObjectIDNameExpr"),
    ("apiVersion: wrong/v9\n"
     "match: [{apiVersion: v1, resource: r, verbs: [get]}]",
     "unsupported apiVersion"),
])
def test_validation_matrix(doc, msg):
    with pytest.raises(RuleValidationError, match=msg):
        parse_rule_configs(doc)


def test_multi_doc_parse_and_empty_docs():
    docs = parse_rule_configs("""
---
match: [{apiVersion: v1, resource: a, verbs: [get]}]
check: [{tpl: "a:{{name}}#v@user:{{user.name}}"}]
---
# empty doc skipped
---
metadata: {name: second}
match: [{apiVersion: apps/v1, resource: b, verbs: [list]}]
""")
    assert len(docs) == 2
    assert docs[1].name == "second"


# -- matcher (TestMapMatcherMatch shape) -------------------------------------

def test_matcher_group_version_and_verb_dispatch():
    m = MapMatcher.from_yaml("""
metadata: {name: core-get}
match: [{apiVersion: v1, resource: pods, verbs: [get, list]}]
---
metadata: {name: apps}
match: [{apiVersion: apps/v1, resource: deployments, verbs: [get]}]
---
metadata: {name: wide}
match: [{apiVersion: v1, resource: pods, verbs: [get]}]
""")
    get_pods = m.match(RequestMeta("get", "", "v1", "pods"))
    assert sorted(r.name for r in get_pods) == ["core-get", "wide"]
    assert [r.name for r in m.match(RequestMeta("list", "", "v1", "pods"))] \
        == ["core-get"]
    assert [r.name for r in
            m.match(RequestMeta("get", "apps", "v1", "deployments"))] \
        == ["apps"]
    # wrong group/version/verb -> no match
    assert m.match(RequestMeta("get", "apps", "v2", "deployments")) == []
    assert m.match(RequestMeta("delete", "", "v1", "pods")) == []


# -- tupleSets ---------------------------------------------------------------

def test_tupleset_generates_per_label_and_validates_items():
    rule = _rule("""
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
check:
  - tupleSet: >-
      object.metadata.labels.keys().map_each(
        "pod:" + namespacedName + "#label-" + this + "@user:" + user.name)
""")
    body = {"metadata": {"name": "api", "namespace": "t",
                         "labels": {"a": "1", "b": "2"}}}
    rels = rule.checks[0].generate(
        _input(resource="pods", ns="t", name="api", body=body))
    assert sorted(r.resource_relation for r in rels) == ["label-a", "label-b"]

    bad = _rule("""
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
check: [{tupleSet: "['not-a-relationship']"}]
""")
    with pytest.raises(ExprError, match="item 0"):
        bad.checks[0].generate(_input(resource="pods"))

    notalist = _rule("""
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
check: [{tupleSet: "user.name"}]
""")
    with pytest.raises(ExprError, match="list"):
        notalist.checks[0].generate(_input(resource="pods"))


def test_tupleset_rejected_where_single_rel_required():
    with pytest.raises(CompileError, match="not allowed here"):
        _rule("""
match: [{apiVersion: v1, resource: pods, verbs: [delete]}]
update:
  deleteByFilter: [{tupleSet: "['a:b#c@d:e']"}]
""")


# -- if conditions -----------------------------------------------------------

def test_if_conditions_matrix():
    rule = _rule("""
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
if:
  - 'user.name == "alice" || "admins" in user.groups'
  - 'request.verb == "create"'
check: [{tpl: "pod:{{name}}#create@user:{{user.name}}"}]
""")
    assert rule.conditions_pass(_input(resource="pods", user="alice"))
    assert rule.conditions_pass(
        _input(resource="pods", user="bob", groups=("admins",)))
    assert not rule.conditions_pass(_input(resource="pods", user="bob"))


def test_if_condition_non_boolean_rejected():
    rule = _rule("""
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
if: ['user.name']
""")
    with pytest.raises(ExprError, match="bool"):
        rule.conditions_pass(_input(resource="pods"))


# -- structured templates -----------------------------------------------------

def test_input_conversion_matrix():
    """Input → evaluation-data conversion parity with the reference's
    TestConvertToBloblangInput matrix (rules_test.go:1755-2003): user
    extra fields, groups, multi-value headers, the resourceId alias,
    nested object metadata merge, and the empty/missing edge cases."""
    from spicedb_kubeapi_proxy_tpu.rules.input import (
        RequestInfo,
        ResolveInput,
        UserInfo,
    )

    # basic input with user extra fields + multi-value headers
    inp = ResolveInput(
        name="test-pod", namespace="default",
        namespaced_name="default/test-pod",
        request=RequestInfo(verb="create", api_group="v1", api_version="v1",
                            resource="pods", name="test-pod",
                            namespace="default"),
        user=UserInfo(name="test-user", uid="uid123",
                      groups=["group1", "group2"],
                      extra={"department": ["engineering", "security"],
                             "role": ["admin"],
                             "project": ["alpha", "beta", "gamma"]}),
        headers={"Authorization": "Bearer token123",
                 "X-Custom": "value1"},
        object=None, body=None,
    )
    d = inp.template_data()
    assert d["name"] == "test-pod"
    assert d["namespacedName"] == "default/test-pod"
    assert d["resourceId"] == "default/test-pod"  # alias, same value
    assert d["request"]["verb"] == "create"
    assert d["request"]["apiGroup"] == "v1"
    assert d["user"]["uid"] == "uid123"
    assert d["user"]["groups"] == ["group1", "group2"]
    assert d["user"]["extra"]["project"] == ["alpha", "beta", "gamma"]
    assert d["headers"]["Authorization"] == "Bearer token123"
    # CEL-shape: namespace spelled resourceNamespace (rules.go:467-518)
    c = inp.condition_data()
    assert c["resourceNamespace"] == "default"
    assert c["user"]["extra"]["role"] == ["admin"]

    # object metadata with nested structure: metadata hoisted beside object
    inp2 = ResolveInput(
        name="cm", namespace="ns1", namespaced_name="ns1/cm",
        request=RequestInfo(verb="create", resource="configmaps",
                            namespace="ns1"),
        user=UserInfo(name="u"),
        headers={},
        body=None,
        object={"metadata": {"name": "cm",
                             "labels": {"env": "prod", "team": "platform"},
                             "annotations": {"a/b": "c"}},
                "data": {"k": "v"}},
    )
    d2 = inp2.template_data()
    assert d2["metadata"]["labels"]["env"] == "prod"
    assert d2["object"]["data"]["k"] == "v"
    # expressions traverse the merged shape
    from spicedb_kubeapi_proxy_tpu.rules.expr import compile_template
    assert compile_template(
        "{{metadata.labels.team}}").evaluate(d2) == "platform"

    # empty extra/headers and a user with no groups
    inp3 = ResolveInput(
        name="x", namespace="", namespaced_name="x",
        request=RequestInfo(verb="get", resource="namespaces", name="x"),
        user=UserInfo(name="solo", extra={}),
        headers={},
        object=None, body=None,
    )
    d3 = inp3.template_data()
    assert d3["user"]["extra"] == {}
    assert d3["user"]["groups"] == []
    assert d3["headers"] == {}
    assert d3["resourceId"] == "x"  # cluster-scoped: no namespace prefix


def test_structured_template_round_trip():
    rule = _rule("""
match: [{apiVersion: v1, resource: namespaces, verbs: [create]}]
update:
  creates:
    - resource: {type: namespace, id: "{{name}}", relation: creator}
      subject: {type: user, id: "{{user.name}}"}
    - resource: {type: namespace, id: "{{name}}", relation: viewer}
      subject: {type: group, id: devs, relation: member}
""")
    rels = [r.generate(_input())[0] for r in rule.update.creates]
    assert str(rels[0]) == "namespace:dev#creator@user:alice"
    assert str(rels[1]) == "namespace:dev#viewer@group:devs#member"
