"""Socket-level e2e: real HTTP through the proxy server to a real-HTTP fake
kube upstream — the whole handler chain, header authn, dual-write, list
filtering, watch streaming over chunked encoding, health and metrics.

Plays the role of the reference's embedded_integration_test.go +
proxy_test.go smoke paths, with FakeKube standing in for envtest.
"""

import asyncio
import json

import pytest

from spicedb_kubeapi_proxy_tpu.proxy.options import Options
from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient

from fake_kube import FakeKube, serve_upstream

RULES = open("/root/reference/deploy/rules.yaml").read()


class HttpClient:
    """Tiny raw asyncio HTTP client for tests."""

    def __init__(self, port: int, user: str = "alice"):
        self.port = port
        self.user = user

    async def request(self, method: str, target: str, body=None,
                      stream: bool = False, extra_headers=()):
        reader, writer = await asyncio.open_connection("127.0.0.1", self.port)
        data = json.dumps(body).encode() if body is not None else b""
        headers = [f"{method} {target} HTTP/1.1",
                   f"Host: 127.0.0.1:{self.port}",
                   f"X-Remote-User: {self.user}",
                   "Content-Type: application/json",
                   f"Content-Length: {len(data)}",
                   *extra_headers,
                   "Connection: close", "", ""]
        writer.write("\r\n".join(headers).encode() + data)
        await writer.drain()
        status_line = await reader.readline()
        status = int(status_line.split(b" ")[1])
        resp_headers = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            resp_headers[k.strip().lower()] = v.strip()
        if stream:
            return status, resp_headers, (reader, writer)
        if "chunked" in resp_headers.get("transfer-encoding", ""):
            chunks = []
            while True:
                size = int((await reader.readline()).strip() or b"0", 16)
                if size == 0:
                    break
                chunks.append(await reader.readexactly(size))
                await reader.readline()
            bodyb = b"".join(chunks)
        else:
            n = int(resp_headers.get("content-length", 0))
            bodyb = await reader.readexactly(n) if n else await reader.read()
        writer.close()
        return status, resp_headers, bodyb

    async def read_chunk(self, reader):
        size = int((await reader.readline()).strip() or b"0", 16)
        if size == 0:
            return None
        data = await reader.readexactly(size)
        await reader.readline()
        return data


@pytest.fixture()
def env(tmp_path):
    return str(tmp_path / "dtx.sqlite")


def test_server_stop_drains_idle_watch_connections():
    """Graceful stop with an idle watch stream open must complete within
    the grace period: idle streaming handlers never write, so they only
    notice a dead peer on write — stop() cancels them after the grace
    instead of blocking in wait_closed() forever."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.proxy.demo import build

        cfg = build(port=0)
        await cfg.run()
        # open a watch as alice and read just the response headers,
        # leaving the (idle) stream open
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", cfg.server.port)
        writer.write(b"GET /api/v1/namespaces?watch=true HTTP/1.1\r\n"
                     b"Host: x\r\nX-Remote-User: alice\r\n\r\n")
        await writer.drain()
        line = await asyncio.wait_for(reader.readline(), timeout=10)
        assert b"200" in line
        t0 = asyncio.get_running_loop().time()
        await asyncio.wait_for(cfg.server.stop(grace=1.0), timeout=10)
        assert asyncio.get_running_loop().time() - t0 < 8
        writer.close()
        await cfg.workflow.shutdown()
    asyncio.run(go())


def test_demo_stack_end_to_end():
    """`make demo` wiring (proxy/demo.py): the self-contained stack must
    serve per-user-isolated lists, gets, and a dual-write create over
    real HTTP with nothing external."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.proxy.demo import build

        cfg = build(port=0)
        await cfg.run()
        try:
            alice = HttpClient(cfg.server.port, "alice")
            carol = HttpClient(cfg.server.port, "carol")

            async def names(client):
                status, _, body = await client.request(
                    "GET", "/api/v1/namespaces")
                assert status == 200, body
                return [i["metadata"]["name"]
                        for i in json.loads(body)["items"]]

            assert await names(alice) == ["dev"]
            assert await names(carol) == ["prod"]
            # pods inherit namespace visibility via the arrow
            status, _, body = await alice.request("GET", "/api/v1/pods")
            assert status == 200
            assert [i["metadata"]["namespace"]
                    for i in json.loads(body)["items"]] == ["dev"]
            # cross-user get denied; own get allowed
            status, _, _ = await carol.request(
                "GET", "/api/v1/namespaces/dev")
            assert status in (401, 403, 404)
            status, _, _ = await alice.request(
                "GET", "/api/v1/namespaces/dev")
            assert status == 200
            # dual-write create lands in BOTH the upstream and the graph
            status, _, body = await alice.request(
                "POST", "/api/v1/namespaces",
                body={"metadata": {"name": "mine"}})
            assert status == 201, body
            assert await names(alice) == ["dev", "mine"]
            assert await names(carol) == ["prod"]
        finally:
            await cfg.server.stop()
            await cfg.workflow.shutdown()
    asyncio.run(go())


def test_proto_watch_over_real_server(env):
    """A protobuf watch through the FULL stack — real client socket ->
    proxy server -> HttpUpstream -> real-HTTP fake upstream: the stream
    content-type is the proto streaming variant and frames arrive
    length-prefixed, filtered, and byte-parseable (VERDICT r4 dir. 5)."""
    from spicedb_kubeapi_proxy_tpu.proxy import kubeproto

    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=env,
            bind_port=0,
        ).complete()
        await cfg.run()
        try:
            alice = HttpClient(cfg.server.port, "alice")
            status, _, _ = await alice.request(
                "POST", "/api/v1/namespaces",
                body={"apiVersion": "v1", "kind": "Namespace",
                      "metadata": {"name": "proto-a"}})
            assert status == 201
            status, headers, (reader, writer) = await alice.request(
                "GET", "/api/v1/namespaces?watch=true", stream=True,
                extra_headers=[f"Accept: {kubeproto.CONTENT_TYPE}"])
            assert status == 200
            assert headers.get("content-type") == \
                kubeproto.WATCH_CONTENT_TYPE, headers
            buf = b""
            frame = None
            deadline = asyncio.get_running_loop().time() + 10
            while frame is None:
                assert asyncio.get_running_loop().time() < deadline
                chunk = await asyncio.wait_for(
                    alice.read_chunk(reader), timeout=5)
                assert chunk is not None
                buf += chunk
                if len(buf) >= 4:
                    n = int.from_bytes(buf[:4], "big")
                    if len(buf) >= 4 + n:
                        frame, buf = buf[:4 + n], buf[4 + n:]
            assert kubeproto.watch_frame_key(frame) == ("", "proto-a")
            typ, _ = kubeproto.decode_watch_event(frame[4:])
            assert typ == "ADDED"
            writer.close()
            fake.stop_watches()
        finally:
            await cfg.server.stop()
            await cfg.workflow.shutdown()
            upstream_server.close()
    asyncio.run(go())


def test_full_http_round_trips(env):
    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=env,
            bind_port=0,
            enable_debug_config=True,
        ).complete()
        await cfg.run()
        alice = HttpClient(cfg.server.port, "alice")
        bob = HttpClient(cfg.server.port, "bob")

        # health + metrics need no auth
        status, _, body = await HttpClient(cfg.server.port, "").request(
            "GET", "/readyz")
        assert (status, body) == (200, b"ok")

        # unauthenticated resource request -> 401
        noauth = HttpClient(cfg.server.port, "")
        status, _, _ = await noauth.request("GET", "/api/v1/namespaces")
        assert status == 401

        # dual-write create through real sockets
        status, _, body = await alice.request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "team-a"}})
        assert status == 201, body
        assert json.loads(body)["metadata"]["name"] == "team-a"

        # per-user list isolation
        status, _, body = await alice.request("GET", "/api/v1/namespaces")
        assert [o["metadata"]["name"]
                for o in json.loads(body)["items"]] == ["team-a"]
        status, _, body = await bob.request("GET", "/api/v1/namespaces")
        assert json.loads(body)["items"] == []

        # single get isolation
        status, _, _ = await alice.request("GET", "/api/v1/namespaces/team-a")
        assert status == 200
        status, _, _ = await bob.request("GET", "/api/v1/namespaces/team-a")
        assert status == 403

        # watch: chunked streaming end-to-end
        status, headers, (reader, writer) = await alice.request(
            "GET", "/api/v1/namespaces?watch=true", stream=True)
        assert status == 200
        assert "chunked" in headers.get("transfer-encoding", "")
        first = await asyncio.wait_for(alice.read_chunk(reader), timeout=5)
        ev = json.loads(first)
        assert ev["type"] == "ADDED"
        assert ev["object"]["metadata"]["name"] == "team-a"
        # a new namespace created by alice shows up on the stream
        status2, _, _ = await alice.request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "team-b"}})
        assert status2 == 201
        nxt = await asyncio.wait_for(alice.read_chunk(reader), timeout=5)
        assert json.loads(nxt)["object"]["metadata"]["name"] == "team-b"
        writer.close()

        # metrics rendered (proxy + engine families)
        status, _, body = await noauth.request("GET", "/metrics")
        assert status == 200 and b"proxy_requests_total" in body
        assert b"engine_checks_total" in body
        # sanitized config dump: flag-gated AND authenticated-only,
        # secrets redacted
        status, _, _ = await noauth.request("GET", "/debug/config")
        assert status == 401
        status, _, body = await alice.request("GET", "/debug/config")
        dump = json.loads(body)
        assert status == 200 and dump["engine_endpoint"]
        assert "upstream_token" in dump and dump["upstream_token"] is None

        fake.stop_watches()
        await cfg.server.stop()
        await cfg.workflow.shutdown()
        upstream_server.close()
    asyncio.run(go())


def test_concurrent_lists_fuse_through_batch_window(env):
    """--lookup-batch-window wiring end-to-end: concurrent same-type list
    prefilters from different users fuse into shared device dispatches
    (the grid fast path), and per-user isolation survives the fusion."""
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=env,
            bind_port=0,
            lookup_batch_window=0.02,
        ).complete()
        await cfg.run()
        users = [f"user{i}" for i in range(6)]
        clients = {u: HttpClient(cfg.server.port, u) for u in users}
        for u in users:
            status, _, body = await clients[u].request(
                "POST", "/api/v1/namespaces",
                body={"apiVersion": "v1", "kind": "Namespace",
                      "metadata": {"name": f"ns-{u}"}})
            assert status == 201, body

        async def list_ns(u):
            status, _, body = await clients[u].request(
                "GET", "/api/v1/namespaces")
            assert status == 200
            return [o["metadata"]["name"]
                    for o in json.loads(body)["items"]]

        # under heavy host contention a burst can straggle past the batch
        # window (every "batch" holds one lookup); the guarded property is
        # that concurrent lists CAN fuse, so retry the burst a few times —
        # isolation is asserted on every attempt regardless
        for attempt in range(5):
            batches0 = metrics.counter("engine_lookup_batches_total").value
            lookups0 = metrics.counter("engine_lookups_total").value
            results = await asyncio.gather(*(list_ns(u) for u in users))
            for u, names in zip(users, results):
                assert names == [f"ns-{u}"], (u, names)
            fused = (metrics.counter("engine_lookup_batches_total").value
                     - batches0)
            issued = metrics.counter("engine_lookups_total").value - lookups0
            assert issued >= len(users)
            if 0 < fused < issued:
                break
        else:
            raise AssertionError(
                f"no fusion observed in 5 bursts ({fused}/{issued})")

        await cfg.server.stop()
        await cfg.workflow.shutdown()
        upstream_server.close()
    asyncio.run(go())


def test_inmemory_client(env):
    async def go():
        fake = FakeKube()
        cfg = Options(
            rule_content=RULES,
            upstream=fake,
            workflow_database_path=env,
        ).complete()
        await cfg.workflow.resume_pending()
        alice = InMemoryClient(cfg.server.handle, user="alice")
        resp = await alice.post("/api/v1/namespaces", {
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "mem"}})
        assert resp.status == 201
        resp = await alice.get("/api/v1/namespaces")
        assert [o["metadata"]["name"]
                for o in json.loads(resp.body)["items"]] == ["mem"]
        # /debug/config is flag-gated: default options serve 404 even to
        # an authenticated user
        resp = await alice.get("/debug/config")
        assert resp.status == 404
        await cfg.workflow.shutdown()
    asyncio.run(go())


def test_deploy_files_end_to_end(env):
    """The shipped deploy/ rule set + bootstrap schema serve a full
    create -> isolate -> delete cycle (namespaces and namespaced pods)."""
    async def go():
        fake = FakeKube()
        import os
        deploy = os.path.join(os.path.dirname(__file__), "..", "deploy")
        cfg = Options(
            rule_files=[os.path.join(deploy, "rules.yaml")],
            bootstrap_files=[os.path.join(deploy, "bootstrap.yaml")],
            upstream=fake,
            workflow_database_path=env,
        ).complete()
        await cfg.workflow.resume_pending()
        alice = InMemoryClient(cfg.server.handle, user="alice")
        bob = InMemoryClient(cfg.server.handle, user="bob")

        resp = await alice.post("/api/v1/namespaces", {
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "team-a"}})
        assert resp.status == 201
        # pods in alice's namespace: create, list isolation, delete
        resp = await alice.post("/api/v1/namespaces/team-a/pods", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "api", "namespace": "team-a"}})
        assert resp.status == 201, resp.body
        resp = await bob.post("/api/v1/namespaces/team-a/pods", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "intruder", "namespace": "team-a"}})
        assert resp.status == 403
        resp = await alice.get("/api/v1/pods")
        assert [o["metadata"]["name"]
                for o in json.loads(resp.body)["items"]] == ["api"]
        resp = await bob.get("/api/v1/pods")
        assert json.loads(resp.body)["items"] == []
        resp = await alice.delete("/api/v1/namespaces/team-a/pods/api")
        assert resp.status == 200, resp.body
        # deleteByFilter cleaned up every pod relationship
        from spicedb_kubeapi_proxy_tpu.engine import RelationshipFilter
        assert not cfg.engine.store.exists(
            RelationshipFilter(resource_type="pod"))
        await cfg.workflow.shutdown()
    asyncio.run(go())


def test_options_validation(env):
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options, OptionsError
    with pytest.raises(OptionsError, match="rule file"):
        Options(upstream_url="http://x").validate()
    with pytest.raises(OptionsError, match="upstream"):
        Options(rule_content=RULES).validate()
    with pytest.raises(OptionsError, match="engine endpoint"):
        Options(rule_content=RULES, upstream_url="http://x",
                engine_endpoint="grpc://remote:50051").validate()


def test_token_file_authentication(env, tmp_path):
    """kube static-token-file Bearer authn: valid tokens map to
    user/groups, invalid tokens 401 without falling back to headers
    (reference wires kube's token-file authenticator, authn.go:40-47)."""
    tokens = tmp_path / "tokens.csv"
    tokens.write_text(
        "# comment line\n"
        'tok-alice,alice,u1,"team-alpha,devs"\n'
        "tok-bob,bob,u2\n")

    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=env,
            bind_port=0,
            token_auth_file=str(tokens),
        ).complete()
        await cfg.run()

        class TokenClient(HttpClient):
            def __init__(self, port, token):
                super().__init__(port, user="")
                self.token = token

            async def request(self, method, target, body=None, stream=False):
                # replace the X-Remote-User header with a Bearer token
                import json as _json
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", self.port)
                data = _json.dumps(body).encode() if body is not None else b""
                headers = [f"{method} {target} HTTP/1.1",
                           f"Host: 127.0.0.1:{self.port}",
                           f"Authorization: Bearer {self.token}",
                           "Content-Type: application/json",
                           f"Content-Length: {len(data)}",
                           "Connection: close", "", ""]
                writer.write("\r\n".join(headers).encode() + data)
                await writer.drain()
                status_line = await reader.readline()
                status = int(status_line.split(b" ")[1])
                hdrs = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode().partition(":")
                    hdrs[k.strip().lower()] = v.strip()
                n = int(hdrs.get("content-length", 0))
                out = await reader.readexactly(n) if n else b""
                writer.close()
                return status, hdrs, out

        alice = TokenClient(cfg.server.port, "tok-alice")
        bob = TokenClient(cfg.server.port, "tok-bob")
        wrong = TokenClient(cfg.server.port, "nope")

        status, _, body = await alice.request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "tok-ns"}})
        assert status == 201, body
        status, _, body = await alice.request("GET", "/api/v1/namespaces")
        assert [o["metadata"]["name"]
                for o in json.loads(body)["items"]] == ["tok-ns"]
        status, _, body = await bob.request("GET", "/api/v1/namespaces")
        assert json.loads(body)["items"] == []
        # invalid bearer: 401, not a fall-through to anonymous/headers
        status, _, _ = await wrong.request("GET", "/api/v1/namespaces")
        assert status == 401
        # non-ASCII bearer: still a clean 401, never a 500
        weird = TokenClient(cfg.server.port, "caf\xe9")
        status, _, _ = await weird.request("GET", "/api/v1/namespaces")
        assert status == 401
        # the uid column reaches the first-class UserInfo field rules
        # template on ({{user.uid}})
        from spicedb_kubeapi_proxy_tpu.proxy.authn import (
            TokenFileAuthenticator,
        )
        u = TokenFileAuthenticator(str(tokens)).authenticate_token(
            "tok-alice")
        assert (u.name, u.uid, u.groups) == (
            "alice", "u1", ["team-alpha", "devs"])

        await cfg.server.stop()
        await cfg.workflow.shutdown()
        upstream_server.close()
    asyncio.run(go())


def test_concurrency_soak_cross_feature(env):
    """Cross-feature soak: concurrent dual-writes (creates + deletes),
    batched list prefilters, live watch streams, and the hub's recompute
    machinery all churning against one engine for a few hundred
    operations. Invariants at quiesce (reference proxy_test.go:106-111):
    zero leftover lock tuples, per-user list isolation equals the
    surviving set, and every user's watch saw their own creates."""
    from spicedb_kubeapi_proxy_tpu.engine import RelationshipFilter

    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=env,
            bind_port=0,
            lookup_batch_window=0.005,
        ).complete()
        await cfg.run()
        users = [f"soak{i}" for i in range(4)]
        clients = {u: HttpClient(cfg.server.port, u) for u in users}
        per_user = 12
        survivors = {u: set() for u in users}
        watch_seen = {u: set() for u in users}

        async def watcher(u):
            c = HttpClient(cfg.server.port, u)
            status, _, (reader, writer) = await c.request(
                "GET", "/api/v1/namespaces?watch=true", stream=True)
            assert status == 200
            try:
                while True:
                    chunk = await asyncio.wait_for(c.read_chunk(reader),
                                                   timeout=20)
                    if chunk is None:
                        break
                    ev = json.loads(chunk)
                    if ev["type"] in ("ADDED", "MODIFIED"):
                        watch_seen[u].add(ev["object"]["metadata"]["name"])
            except (asyncio.TimeoutError, asyncio.IncompleteReadError):
                pass
            finally:
                writer.close()

        watch_tasks = [asyncio.create_task(watcher(u)) for u in users]
        await asyncio.sleep(0.2)  # watchers registered before churn

        async def churn(u):
            c = clients[u]
            for i in range(per_user):
                name = f"ns-{u}-{i}"
                status, _, body = await c.request(
                    "POST", "/api/v1/namespaces",
                    body={"apiVersion": "v1", "kind": "Namespace",
                          "metadata": {"name": name}})
                assert status == 201, (u, i, body)
                survivors[u].add(name)
                # interleave lists (batched prefilters) with the writes;
                # 401 here is the prefilter-wait timeout (reference
                # responsefilterer.go:44 -> 401 body), which a saturated
                # host can legitimately hit — isolation is only checkable
                # on completed lists
                status, _, body = await c.request(
                    "GET", "/api/v1/namespaces")
                assert status in (200, 401), (u, status)
                if status == 200:
                    names = {o["metadata"]["name"]
                             for o in json.loads(body)["items"]}
                    assert names <= survivors[u], (u, names - survivors[u])
                if i % 3 == 2:
                    victim = f"ns-{u}-{i - 1}"
                    status, _, _ = await c.request(
                        "DELETE", f"/api/v1/namespaces/{victim}")
                    assert status in (200, 202), (u, victim, status)
                    survivors[u].discard(victim)

        await asyncio.gather(*(churn(u) for u in users))

        # quiesce: poll until every user's list settles on the surviving
        # set (deletes, hub recomputes, and watch frames drain at
        # host-load-dependent speed; a fixed sleep flakes under contention)
        async def settled(u):
            status, _, body = await clients[u].request(
                "GET", "/api/v1/namespaces")
            if status != 200:  # prefilter-wait timeout under load: retry
                return None
            return {o["metadata"]["name"]
                    for o in json.loads(body)["items"]}

        deadline = asyncio.get_running_loop().time() + 20
        last = {}
        while True:
            last = {u: await settled(u) for u in users}
            if all(last[u] is not None and last[u] == survivors[u]
                   for u in users):
                break
            if asyncio.get_running_loop().time() > deadline:
                raise AssertionError(
                    {u: ("prefilter timeout" if last[u] is None
                         else last[u] ^ survivors[u])
                     for u in users if last[u] != survivors[u]})
            await asyncio.sleep(0.25)

        # the reference's invariant: no leftover lock tuples
        assert not cfg.engine.store.exists(
            RelationshipFilter(resource_type="lock"))

        # watch frames drain asynchronously of the list path: wait until
        # every watcher has seen its surviving creates before cancelling
        deadline = asyncio.get_running_loop().time() + 20
        while not all(survivors[u] <= watch_seen[u] for u in users):
            if asyncio.get_running_loop().time() > deadline:
                break  # the assertions below report the gap
            await asyncio.sleep(0.25)

        for t in watch_tasks:
            t.cancel()
        await asyncio.gather(*watch_tasks, return_exceptions=True)
        for u in users:
            # created-then-quickly-deleted objects may legitimately never
            # surface (a buffered frame is dropped when the deny beats the
            # allow — reference responsefilterer.go:628-710); everything
            # that SURVIVED must have been seen, and nothing foreign
            missed = survivors[u] - watch_seen[u]
            assert not missed, (u, missed)
            created = {f"ns-{u}-{i}" for i in range(per_user)}
            foreign = watch_seen[u] - created
            assert not foreign, (u, foreign)

        fake.stop_watches()
        await cfg.server.stop()
        await cfg.workflow.shutdown()
        upstream_server.close()
    asyncio.run(go())


@pytest.mark.parametrize("lock_mode", ["Pessimistic", "Optimistic"])
def test_chaos_storm_transient_kube_failures(env, lock_mode):
    """Chaos leg 1 — transient upstream faults under concurrent churn:
    kube TRANSPORT failures (connection killed mid-request) injected
    while three users create namespaces. The workflow retry loop
    (<=5 attempts, backoff — reference workflow.go:211-222 retries only
    transport errors) must absorb every burst shorter than the budget;
    every create must be fully atomic per name (response == upstream ==
    graph == list visibility), and no lock tuples survive (the crash
    matrix run as a storm, reference proxy_test.go:106-111). A definitive
    kube 500 RESPONSE, by contrast, is a rejection: rolled back without
    retry (workflow.go:243-245) — asserted deterministically at the end."""
    from spicedb_kubeapi_proxy_tpu.engine import RelationshipFilter

    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=env,
            lock_mode=lock_mode,
            bind_port=0,
            # this storm orchestrates its own fault budgets; concurrent
            # bursts can exceed the breaker threshold back-to-back, and a
            # tripped breaker would fail ops the workflow budget should
            # absorb (the breaker has dedicated coverage in test_chaos.py)
            breaker_failure_threshold=100,
        ).complete()
        await cfg.run()
        users = [f"storm{i}" for i in range(3)]
        clients = {u: HttpClient(cfg.server.port, u) for u in users}
        status_by_name: dict[str, tuple] = {}

        async def churn(u, idx):
            c = clients[u]
            for i in range(8):
                if (i + idx) % 3 == 1:
                    # burst of killed connections, below the 5-attempt
                    # budget; concurrent writes share the fault queue, so
                    # which op eats how many faults is nondeterministic
                    # by design
                    fake.fail_next(
                        2, exception=ConnectionResetError("injected"))
                name = f"st-{u}-{i}"
                status, _, _ = await c.request(
                    "POST", "/api/v1/namespaces",
                    body={"apiVersion": "v1", "kind": "Namespace",
                          "metadata": {"name": name}})
                status_by_name[name] = (u, status)

        await asyncio.gather(*(churn(u, i) for i, u in enumerate(users)))

        deadline = asyncio.get_running_loop().time() + 25
        while (cfg.engine.store.exists(RelationshipFilter(
                resource_type="lock"))
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.25)
        assert not cfg.engine.store.exists(
            RelationshipFilter(resource_type="lock"))

        lists = {}
        for u in users:
            status, _, body = await clients[u].request(
                "GET", "/api/v1/namespaces")
            assert status == 200
            lists[u] = {o["metadata"]["name"]
                        for o in json.loads(body)["items"]}

        landed = 0
        for name, (u, status) in status_by_name.items():
            in_upstream = ("namespaces", "", name) in fake.objects
            in_graph = cfg.engine.store.exists(RelationshipFilter(
                resource_type="namespace", resource_id=name))
            visible = name in lists[u]
            if status == 201:
                assert in_upstream and in_graph and visible, (
                    name, status, in_upstream, in_graph, visible)
                landed += 1
            else:
                assert not in_upstream and not in_graph and not visible, (
                    name, status, in_upstream, in_graph, visible)
        # bursts stay under the retry budget: everything must have landed
        assert landed == len(status_by_name), (landed, len(status_by_name))

        # a definitive 500 RESPONSE (nothing else in flight): rejection,
        # rolled back without retry — reference workflow.go:243-245
        fake.fail_next(1, status=500)
        status, _, _ = await clients[users[0]].request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "st-rejected"}})
        assert status == 500
        assert ("namespaces", "", "st-rejected") not in fake.objects
        assert not cfg.engine.store.exists(RelationshipFilter(
            resource_type="namespace", resource_id="st-rejected"))
        assert not cfg.engine.store.exists(
            RelationshipFilter(resource_type="lock"))

        fake.stop_watches()
        await cfg.server.stop()
        await cfg.workflow.shutdown()
        upstream_server.close()
    asyncio.run(go())


def test_chaos_crash_mid_dual_write_recovers_on_resume(env):
    """Chaos leg 2 — a failpoint 'process death' mid-dual-write at the
    HTTP layer: the client sees the dual-write timeout, the instance
    stays suspended with its lock held (exactly a crashed process), and
    resume_pending() — what cfg.run() does at boot — replays the event
    log, completes the kube write, and releases the lock: the create
    eventually lands even though its HTTP response was an error
    (at-least-once durable dual-write, reference workflow.go + the e2e
    crash matrix, run through the full server)."""
    from spicedb_kubeapi_proxy_tpu.authz import middleware
    from spicedb_kubeapi_proxy_tpu.engine import RelationshipFilter
    from spicedb_kubeapi_proxy_tpu.utils.failpoints import failpoints

    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=env,
            bind_port=0,
        ).complete()
        await cfg.run()
        alice = HttpClient(cfg.server.port, "alice")

        # don't sit out the full 30s dual-write wait for the staged crash
        saved_timeout = middleware.WORKFLOW_RESULT_TIMEOUT
        middleware.WORKFLOW_RESULT_TIMEOUT = 3.0
        failpoints.enable("panicKubeWrite", budget=1)
        status, _, body = await alice.request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "crashy"}})
        middleware.WORKFLOW_RESULT_TIMEOUT = saved_timeout
        # the workflow is suspended (simulated dead process): the client
        # saw a timeout and the half-applied state is held under the lock
        assert status >= 500, (status, body)
        assert cfg.engine.store.exists(
            RelationshipFilter(resource_type="lock"))
        assert ("namespaces", "", "crashy") not in fake.objects
        failpoints.disable_all()

        # "restart": resume from the event log, as cfg.run() does at boot
        resumed = await cfg.workflow.resume_pending()
        assert resumed, "the suspended instance must be found"
        deadline = asyncio.get_running_loop().time() + 20
        while (cfg.engine.store.exists(RelationshipFilter(
                resource_type="lock"))
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.25)
        assert not cfg.engine.store.exists(
            RelationshipFilter(resource_type="lock"))
        assert ("namespaces", "", "crashy") in fake.objects
        assert cfg.engine.store.exists(RelationshipFilter(
            resource_type="namespace", resource_id="crashy"))
        status, _, body = await alice.request("GET", "/api/v1/namespaces")
        assert status == 200
        assert "crashy" in {o["metadata"]["name"]
                            for o in json.loads(body)["items"]}

        fake.stop_watches()
        await cfg.server.stop()
        await cfg.workflow.shutdown()
        upstream_server.close()
    asyncio.run(go())


def test_chaos_crash_storm_converges_after_resumes(env):
    """Chaos leg 3 — a storm of simulated process deaths: failpoints at
    BOTH side-effect edges (SpiceDB write, kube write) strike repeatedly
    while two users create namespaces concurrently. Whichever in-flight
    workflow eats a fault suspends exactly like a crashed process (its
    client sees an error); repeated resume_pending() cycles — process
    restarts — must drain every suspended instance to completion: every
    create eventually lands atomically, locks reach zero, and the event
    logs replay deterministically (reference e2e crash matrix as a storm,
    proxy_test.go:650-830)."""
    from spicedb_kubeapi_proxy_tpu.authz import middleware
    from spicedb_kubeapi_proxy_tpu.engine import RelationshipFilter
    from spicedb_kubeapi_proxy_tpu.utils.failpoints import failpoints

    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=env,
            bind_port=0,
        ).complete()
        await cfg.run()
        users = ["stormA", "stormB"]
        clients = {u: HttpClient(cfg.server.port, u) for u in users}

        saved_timeout = middleware.WORKFLOW_RESULT_TIMEOUT
        middleware.WORKFLOW_RESULT_TIMEOUT = 2.0
        try:
            async def churn(u, idx):
                c = clients[u]
                for i in range(6):
                    if (i + idx) % 3 == 0:
                        failpoints.enable("panicKubeWrite", budget=1)
                    elif (i + idx) % 3 == 1:
                        failpoints.enable("panicWriteSpiceDB", budget=1)
                    await c.request(
                        "POST", "/api/v1/namespaces",
                        body={"apiVersion": "v1", "kind": "Namespace",
                              "metadata": {"name": f"cr-{u}-{i}"}})

            await asyncio.gather(*(churn(u, i)
                                   for i, u in enumerate(users)))
        finally:
            middleware.WORKFLOW_RESULT_TIMEOUT = saved_timeout
            failpoints.disable_all()

        # repeated "restarts" until every suspended instance drains
        deadline = asyncio.get_running_loop().time() + 30
        while cfg.workflow.pending_count():
            assert asyncio.get_running_loop().time() < deadline, \
                f"{cfg.workflow.pending_count()} instances never drained"
            await cfg.workflow.resume_pending()
            await asyncio.sleep(0.25)

        assert not cfg.engine.store.exists(
            RelationshipFilter(resource_type="lock"))
        lists = {}
        for u in users:
            status, _, body = await clients[u].request(
                "GET", "/api/v1/namespaces")
            assert status == 200
            lists[u] = {o["metadata"]["name"]
                        for o in json.loads(body)["items"]}
        for u in users:
            for i in range(6):
                name = f"cr-{u}-{i}"
                in_upstream = ("namespaces", "", name) in fake.objects
                in_graph = cfg.engine.store.exists(RelationshipFilter(
                    resource_type="namespace", resource_id=name))
                visible = name in lists[u]
                # faults are one-shot: after enough restarts every create
                # must have landed everywhere
                assert in_upstream and in_graph and visible, (
                    name, in_upstream, in_graph, visible)

        fake.stop_watches()
        await cfg.server.stop()
        await cfg.workflow.shutdown()
        upstream_server.close()
    asyncio.run(go())


def test_upstream_dying_mid_request_surfaces_connection_error(env):
    """An upstream that closes the socket before sending a status line
    must surface as a connection error (which retry paths absorb), never
    a bare IndexError from the status-line parse — found by a soak where
    killed-connection faults printed IndexError tracebacks."""
    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=env,
            bind_port=0,
            # 8 consecutive injected transport failures below; keep the
            # breaker out of the way (dedicated coverage in test_chaos.py)
            breaker_failure_threshold=100,
        ).complete()
        await cfg.run()
        alice = HttpClient(cfg.server.port, "alice")
        # a dual-write whose kube writes ALL die mid-request: the workflow
        # retries then reports cleanly (5xx), no IndexError anywhere.
        # The transport layer never retries POSTs, so the workflow budget
        # consumes exactly the 6 faults (5+1 attempts) and nothing leaks
        # into the later requests
        fake.fail_next(6, exception=ConnectionResetError("mid-request"))
        status, _, body = await alice.request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "dying"}})
        assert status >= 500, (status, body)
        assert b"IndexError" not in body
        # ONE killed connection on a read: absorbed by the transport
        # layer's idempotent-GET retry (utils/resilience.py)
        fake.fail_next(1, exception=ConnectionResetError("mid-request"))
        status, _, body = await alice.request("GET", "/api/v1/namespaces")
        assert status == 200
        # a read whose retry ALSO dies: clean 5xx, no IndexError
        fake.fail_next(2, exception=ConnectionResetError("mid-request"))
        status, _, body = await alice.request("GET", "/api/v1/namespaces")
        assert status >= 500
        assert b"IndexError" not in body
        # and the path recovers once the upstream behaves
        status, _, _ = await alice.request("GET", "/api/v1/namespaces")
        assert status == 200

        fake.stop_watches()
        await cfg.server.stop()
        await cfg.workflow.shutdown()
        upstream_server.close()
    asyncio.run(go())


def test_engine_probe_timeout(env):
    """--engine-probe-timeout: a responsive backend passes boot; the probe
    rejects rather than hangs when the device cannot answer (validated
    against a genuinely hung TPU tunnel during development — here the
    cpu backend answers, and the flag=0 default skips probing)."""
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        _probe_device_backend)

    _probe_device_backend(60)  # cpu backend: must pass quickly
    # and the Options path accepts the field
    cfg = Options(
        rule_content=RULES,
        upstream=FakeKube(),
        workflow_database_path=env,
        engine_probe_timeout=60,
    ).complete()
    assert cfg.engine is not None
