"""BAD: every construct here must produce a loop-blocking finding."""
import queue
import sqlite3
import time


work_q = queue.Queue()


async def sleeps_on_loop():
    time.sleep(0.5)  # finding: blocking sleep


async def blocking_queue_get():
    item = work_q.get()  # finding: non-awaited queue get
    return item


async def blocking_queue_put(item):
    work_q.put(item)  # finding: non-awaited queue put


async def blocking_sqlite(db):
    db.execute("INSERT INTO t VALUES (1)")  # finding: sqlite execute
    db.commit()  # finding: sqlite commit


async def opens_sqlite():
    return sqlite3.connect("x.db")  # finding: blocking sqlite open


async def device_sync(arr):
    arr.block_until_ready()  # finding: device sync on the loop
