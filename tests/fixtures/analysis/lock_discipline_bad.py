"""BAD: every construct here must produce a lock-discipline finding."""
import os
import threading
import time

import jax


class Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self.host_lock = threading.Lock()
        self._tenants = {}
        self._subs = {}

    def sleeps_under_lock(self):
        with self._lock:
            time.sleep(0.1)  # finding: sleep while held

    def fsync_under_lock(self, fd):
        with self.host_lock:
            os.fsync(fd)  # finding: fsync while held

    def device_put_under_lock(self, x):
        with self.host_lock:
            return jax.device_put(x)  # finding: device transfer held

    async def awaits_under_lock(self, fut):
        with self._lock:
            await fut  # finding: await under a sync lock

    def unlocked_iteration(self):
        for k, v in self._tenants.items():  # finding: unlocked iter
            print(k, v)

    def unlocked_snapshot(self):
        return list(self._subs)  # finding: unlocked snapshot
