"""BAD: every construct here must produce a jit-stability finding."""
import threading
from functools import partial

import jax
import numpy as np


def _kernel(meta, x, n):
    if n > 4:  # finding: Python branch on traced arg n
        x = x + 1
    for _ in range(n):  # finding: range() over traced arg n
        x = x * 2
    y = np.sum(x)  # finding: numpy on traced arg x
    z = x.item()  # finding: .item() inside a jitted body
    return y + z


def build(meta):
    return jax.jit(partial(_kernel, meta))


def _mesh_kernel(meta, edges, n_steps):
    for _ in range(n_steps):  # finding: traced n_steps through the
        edges = edges + 1  # shard_map wrapper + assignment chain
    return edges


def build_mesh(meta):
    from jax.experimental.shard_map import shard_map

    fn = partial(_mesh_kernel, meta)
    smapped = shard_map(fn, in_specs=None, out_specs=None)
    return jax.jit(smapped)


def _mesh_kernel_b(meta, z, m):
    for _ in range(m):  # finding: reached through the SECOND function's
        z = z * 2  # same-named locals (scope-aware resolution)
    return z


def build_mesh_b(meta):
    # deliberately the SAME local names as build_mesh: a module-global
    # assignment map would resolve `fn`/`smapped` to build_mesh's chain
    # and never check _mesh_kernel_b
    from jax.experimental.shard_map import shard_map

    fn = partial(_mesh_kernel_b, meta)
    smapped = shard_map(fn, in_specs=None, out_specs=None)
    return jax.jit(smapped)


def _occ_kernel(meta, v, crossover):
    occ = v.astype("float32").mean()  # derived from traced v
    frac = occ / meta.span
    if frac <= crossover:  # finding: Python branch on a DERIVED traced
        v = v + 1  # value — the push/pull switch baked into the trace
    return v


def build_occ(meta):
    return jax.jit(partial(_occ_kernel, meta))


_lock = threading.Lock()


def host_sync_under_lock(arr):
    with _lock:
        return arr.item()  # finding: host sync while holding a lock


def device_get_under_lock(arr):
    with _lock:
        return jax.device_get(arr)  # finding: host sync under lock
