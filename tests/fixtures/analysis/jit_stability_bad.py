"""BAD: every construct here must produce a jit-stability finding."""
import threading
from functools import partial

import jax
import numpy as np


def _kernel(meta, x, n):
    if n > 4:  # finding: Python branch on traced arg n
        x = x + 1
    for _ in range(n):  # finding: range() over traced arg n
        x = x * 2
    y = np.sum(x)  # finding: numpy on traced arg x
    z = x.item()  # finding: .item() inside a jitted body
    return y + z


def build(meta):
    return jax.jit(partial(_kernel, meta))


_lock = threading.Lock()


def host_sync_under_lock(arr):
    with _lock:
        return arr.item()  # finding: host sync while holding a lock


def device_get_under_lock(arr):
    with _lock:
        return jax.device_get(arr)  # finding: host sync under lock
