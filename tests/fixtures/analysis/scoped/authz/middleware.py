"""BAD scoped fixture (path ends authz/middleware.py so the fail-closed
pass applies): every handler/producer here must produce a finding."""

RETRY_AFTER_CAP_S = 60


def swallows_silently(engine):
    try:
        return engine.check()
    except Exception:
        pass  # finding: swallowed on the decision path


def logs_and_falls_through(engine, log):
    try:
        return engine.check()
    except ValueError as e:
        log.warning("check failed: %s", e)  # finding: log is not disposal


def unclamped_retry_after(resp, e):
    resp.headers["Retry-After"] = str(e.retry_after)  # finding: producer
    return resp


def _fail_closed_503(e, resp):
    resp.headers["Retry-After"] = str(e.retry_after)  # finding: builder
    return resp                                       # lost its clamp
