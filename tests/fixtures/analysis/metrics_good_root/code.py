"""GOOD metrics fixture: one kind per family, consistent label keys,
literal names, and a docs table agreeing in both directions."""


def use(metrics):
    metrics.counter("app_requests_total", verb="get").inc()
    metrics.counter("app_requests_total", verb="list").inc(2)
    metrics.histogram("app_request_seconds", buckets=[0.1, 1]).observe(0.2)
    metrics.gauge("app_inflight").set(3)
    metrics.counter("app_sheds_total", **{"class": "watch"}).inc()
