"""GOOD: the loop-blocking pass must stay quiet on all of this."""
import asyncio
import queue
import time


work_q = queue.Queue()


async def sleeps_async():
    await asyncio.sleep(0.5)  # asyncio sleep is the point


async def threads_the_blocking_call():
    await asyncio.to_thread(time.sleep, 0.5)  # reference, not a call


async def awaited_asyncio_queue(aq: "asyncio.Queue"):
    item = await aq.get()  # awaited: asyncio.Queue
    more = await asyncio.wait_for(aq.get(), timeout=1.0)  # wrapped await
    return item, more


async def nonblocking_queue_probe():
    return work_q.get(block=False)  # explicit non-blocking


async def db_via_thread(db):
    def commit():
        db.execute("INSERT INTO t VALUES (1)")  # sync helper: executor
        db.commit()
    await asyncio.to_thread(commit)


def sync_function_may_block():
    time.sleep(0.5)  # not an async body
