"""GOOD scoped fixture: the fail-closed pass must stay quiet."""

RETRY_AFTER_CAP_S = 60


class DependencyUnavailable(Exception):
    retry_after = 1.0


def _fail_closed_503(e, resp):
    resp.headers["Retry-After"] = str(
        min(RETRY_AFTER_CAP_S, max(1, int(e.retry_after + 0.5))))
    return resp


def reraises(engine):
    try:
        return engine.check()
    except ValueError:
        raise


def raises_domain_error(engine):
    try:
        return engine.check()
    except OSError as e:
        raise DependencyUnavailable(str(e)) from e


def routes_through_builder(engine, resp):
    try:
        return engine.check()
    except DependencyUnavailable as e:
        return _fail_closed_503(e, resp)


def explicit_fallback(engine):
    try:
        return engine.check()
    except KeyError:
        return None  # explicit fallback value: visible disposal


def justified_cleanup(writer):
    try:
        writer.close()
    except Exception:  # noqa: BLE001 - teardown best effort
        pass
