"""BAD metrics fixture: kind conflict, label conflict, dynamic name,
undocumented family; the paired docs table adds a stale row, a kind
mismatch, and a label mismatch."""


def use(metrics, name):
    metrics.counter("app_requests_total", verb="get").inc()
    metrics.gauge("app_requests_total", verb="get").set(1)  # kind conflict
    metrics.counter("app_sheds_total", reason="full").inc()
    metrics.counter("app_sheds_total", tenant="t1").inc()  # label conflict
    metrics.counter(name).inc()  # dynamic name
    metrics.histogram("app_undocumented_seconds").observe(0.1)
    metrics.gauge("app_mismatched_kind").set(2.0)
    metrics.counter("app_mismatched_labels_total", op="check").inc()
