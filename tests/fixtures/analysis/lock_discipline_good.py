"""GOOD: the lock-discipline pass must stay quiet on all of this."""
import asyncio
import threading
import time


class Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self._alock = asyncio.Lock()
        self._tenants = {}
        self._subs = {}

    def sleep_outside_lock(self):
        with self._lock:
            n = len(self._tenants)
        time.sleep(0.1)  # lock already released
        return n

    async def awaits_under_async_lock(self, fut):
        async with self._alock:  # asyncio lock: awaiting is its design
            await fut

    def locked_iteration(self):
        with self._lock:
            for k, v in self._tenants.items():
                print(k, v)

    def snapshot_under_lock_iterate_outside(self):
        with self._lock:
            subs = list(self._subs)
        for s in subs:  # iterating the COPY needs no lock
            print(s)
