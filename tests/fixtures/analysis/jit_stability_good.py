"""GOOD: the jit-stability pass must stay quiet on all of this."""
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _kernel(meta, x, n):
    # meta is partial-bound (static); n is declared static at the jit
    # site below — both may drive Python control flow
    if meta.levels > 1:
        x = x + 1
    for _ in range(n):
        x = x * 2
    probe = np.zeros(meta.pad)  # numpy on STATIC meta traces fine
    return jnp.sum(x) + lax.stop_gradient(x)[0] + probe.shape[0]


def build(meta):
    return jax.jit(partial(_kernel, meta), static_argnames=("n",))


_lock = threading.Lock()


def snapshot_under_lock_sync_outside(arr):
    with _lock:
        dev = arr  # snapshot the reference under the lock
    return dev.item()  # host sync OUTSIDE the critical section
