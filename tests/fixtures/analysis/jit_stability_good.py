"""GOOD: the jit-stability pass must stay quiet on all of this."""
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _kernel(meta, x, n):
    # meta is partial-bound (static); n is declared static at the jit
    # site below — both may drive Python control flow
    if meta.levels > 1:
        x = x + 1
    for _ in range(n):
        x = x * 2
    probe = np.zeros(meta.pad)  # numpy on STATIC meta traces fine
    return jnp.sum(x) + lax.stop_gradient(x)[0] + probe.shape[0]


def build(meta):
    return jax.jit(partial(_kernel, meta), static_argnames=("n",))


def _mesh_kernel(meta, x, k_steps):
    # meta is positional-bound and k_steps KEYWORD-bound through the
    # partial -> shard_map -> assignment chain: both are static, so
    # Python control flow on them is fine (the mesh path's K-step loop)
    for _ in range(k_steps):
        x = x * 2
    if meta.levels > 1:
        x = x + 1
    return x


def build_mesh(meta):
    from jax.experimental.shard_map import shard_map

    fn = partial(_mesh_kernel, meta, k_steps=4)
    smapped = shard_map(fn, in_specs=None, out_specs=None)
    return jax.jit(smapped)


def _occ_kernel(meta, v, crossover):
    # the ISSUE 17 shape: the per-iteration push/pull switch on traced
    # occupancy IS a lax.cond — the derived value never drives Python
    occ = jnp.mean(v.astype(jnp.float32))
    is_push = occ <= crossover
    v = lax.cond(is_push, lambda x: x + 1, lambda x: x * 2, v)
    if v.shape[0] > 4:  # static-shape extraction: no taint
        v = v[:4]
    span = len(meta.programs)  # len() on a derived tuple: still static
    extra = None if span < 2 else occ
    if extra is None:  # identity guard on a derived name: stable under
        return v  # trace, allowed
    return v + extra


def build_occ(meta):
    return jax.jit(partial(_occ_kernel, meta))


_lock = threading.Lock()


def snapshot_under_lock_sync_outside(arr):
    with _lock:
        dev = arr  # snapshot the reference under the lock
    return dev.item()  # host sync OUTSIDE the critical section
