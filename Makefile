# Dev tooling (the reference uses mage targets, magefiles/*.go; this is
# the same surface as plain make).

PY ?= python

.PHONY: test test-quick chaos bench bench-quick bench-smoke serve-dev demo native lint clean

# full suite on the virtual 8-device CPU mesh (tests/conftest.py)
test:
	$(PY) -m pytest tests/ -q

# fast smoke: engine parity + rules + authz only
test-quick:
	$(PY) -m pytest tests/test_engine.py tests/test_rules.py \
	  tests/test_authz.py -q

# failpoint-driven transport chaos: deterministic (no sleeps — backoff
# schedules injected), also part of the default `make test` selection
chaos:
	$(PY) -m pytest -m chaos -q --continue-on-collection-errors

# the headline benchmark (real TPU if reachable, CPU-degraded otherwise)
bench:
	$(PY) bench.py

bench-quick:
	$(PY) bench.py --quick

# CI-sized bench exercising the full hot path including the decision
# cache's repeat-traffic phase (cold vs warm p50 + hit rate on stderr)
bench-smoke: bench-quick

# fully self-contained demo: proxy + in-memory upstream + sample rules
# on http://127.0.0.1:8080 (the reference's `mage dev:up`+`dev:run` flow
# without a kind cluster); it prints curl examples on boot
demo:
	$(PY) -m spicedb_kubeapi_proxy_tpu.proxy.demo

# run a local dev proxy with the in-repo rule set against YOUR apiserver
# (reference `mage dev:run` runs against a kind cluster; set UPSTREAM_URL
# — e.g. a kind/minikube endpoint — or swap in --kubeconfig)
serve-dev:
	$(PY) -m spicedb_kubeapi_proxy_tpu.proxy.cli \
	  --rule-file deploy/rules.yaml \
	  --bootstrap deploy/bootstrap.yaml \
	  --upstream-url $${UPSTREAM_URL:?set UPSTREAM_URL} \
	  --bind-port 8443 --enable-debug-config

# (re)build the native graph-builder core explicitly
native:
	g++ -O3 -std=c++17 -fPIC -shared -pthread \
	  spicedb_kubeapi_proxy_tpu/native/graphcore.cpp \
	  -o spicedb_kubeapi_proxy_tpu/native/libgraphcore.so

lint:
	$(PY) -m compileall -q spicedb_kubeapi_proxy_tpu tests bench.py

clean:
	rm -f spicedb_kubeapi_proxy_tpu/native/libgraphcore.so
	find . -name __pycache__ -type d -exec rm -rf {} +

# flake hunting: loop the suite until it fails (reference
# `mage test:e2eUntilItFails`)
test-until-it-fails:
	while $(PY) -m pytest tests/ -q; do echo "=== pass, again ==="; done
