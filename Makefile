# Dev tooling (the reference uses mage targets, magefiles/*.go; this is
# the same surface as plain make).

PY ?= python
# `verify` uses pipefail, which /bin/sh (dash) lacks
SHELL := /bin/bash

.PHONY: test test-quick chaos chaos-campaign bench bench-quick bench-smoke bench-macro serve-dev demo native lint analyze verify image clean

# full suite on the virtual 8-device CPU mesh (tests/conftest.py)
test:
	$(PY) -m pytest tests/ -q

# fast smoke: engine parity + rules + authz only
test-quick:
	$(PY) -m pytest tests/test_engine.py tests/test_rules.py \
	  tests/test_authz.py -q

# failpoint-driven transport chaos: deterministic (no sleeps — backoff
# schedules injected), also part of the default `make test` selection.
# Slow-marked compositions (subprocess topologies) belong to the CI
# chaos job / `make chaos-campaign`, not this fast gate.
chaos:
	$(PY) -m pytest -m "chaos and not slow" -q --continue-on-collection-errors

# the seeded chaos campaign (chaos/campaign.py): full topology — 2 shard
# groups × 2-peer failover sets of subprocess engine hosts × the planner
# stack — driven by the loadgen open-loop schedule under deterministic
# fault schedules (wire-armed brownouts) and SIGKILL/restart cycles,
# with every safety invariant (never-fail-open, zero-acked-write-loss,
# no-stale-verdict, split-journal-completion, retry-amplification)
# checked after each episode. Fails on ANY violation. One seed names
# one byte-reproducible run (per-seed fault digests in the output).
CHAOS_SEEDS ?= 3
CHAOS_EPISODES ?= short
chaos-campaign:
	$(PY) -m spicedb_kubeapi_proxy_tpu.chaos.campaign \
	  --seeds $(CHAOS_SEEDS) --episodes $(CHAOS_EPISODES)

# the headline benchmark (real TPU if reachable, CPU-degraded otherwise)
bench:
	$(PY) bench.py

bench-quick:
	$(PY) bench.py --quick

# CI-sized bench exercising the full hot path including the decision
# cache's repeat-traffic phase (cold vs warm p50 + hit rate on stderr),
# gated by the relative regression checks (relative = internal to one
# run, so any backend speed works):
#  - tools/write_path_gate.py: zero recompiles under steady-state churn
#    and read-after-write p50 within a fixed ratio of the same run's
#    read-only p50 (the pre-overlay seed sat at 2.16x)
#  - tools/tiered_gate.py: hot-working-set p50 under the 50% device
#    budget within TIERED_RATIO (default 1.3x) of the same run's
#    all-resident p50, oracle parity at the beyond-budget point, and
#    zero recompiles across steady streaming
# One bench run feeds both gates via a temp file (they can't share a
# pipe), removed only on success so a failing run leaves the evidence.
bench-smoke:
	$(PY) bench.py --quick > /tmp/_bench_smoke.json
	$(PY) tools/write_path_gate.py /tmp/_bench_smoke.json
	$(PY) tools/tiered_gate.py /tmp/_bench_smoke.json
	rm -f /tmp/_bench_smoke.json

# open-loop macrobench smoke: ONLY the trace-shaped offered-load sweep
# at --tiny scale (seconds, not minutes) — proves the goodput curve,
# knee estimate, burst p99.9, and SLO attainment all emit
bench-macro:
	$(PY) bench.py --tiny --macro-only

# fully self-contained demo: proxy + in-memory upstream + sample rules
# on http://127.0.0.1:8080 (the reference's `mage dev:up`+`dev:run` flow
# without a kind cluster); it prints curl examples on boot
demo:
	$(PY) -m spicedb_kubeapi_proxy_tpu.proxy.demo

# run a local dev proxy with the in-repo rule set against YOUR apiserver
# (reference `mage dev:run` runs against a kind cluster; set UPSTREAM_URL
# — e.g. a kind/minikube endpoint — or swap in --kubeconfig)
serve-dev:
	$(PY) -m spicedb_kubeapi_proxy_tpu.proxy.cli \
	  --rule-file deploy/rules.yaml \
	  --bootstrap deploy/bootstrap.yaml \
	  --upstream-url $${UPSTREAM_URL:?set UPSTREAM_URL} \
	  --bind-port 8443 --enable-debug-config

# build the serving image deploy/proxy.yaml references
# (spicedb-kubeapi-proxy-tpu:latest). CPU JAX by default; TPU node pools
# pass JAX_EXTRA=tpu. DOCKER=podman works too.
DOCKER ?= docker
JAX_EXTRA ?= cpu
image:
	$(DOCKER) build --build-arg JAX_EXTRA=$(JAX_EXTRA) \
	  -t spicedb-kubeapi-proxy-tpu:latest .

# (re)build the native graph-builder core explicitly
native:
	g++ -O3 -std=c++17 -fPIC -shared -pthread \
	  spicedb_kubeapi_proxy_tpu/native/graphcore.cpp \
	  -o spicedb_kubeapi_proxy_tpu/native/libgraphcore.so

# ruff (config in pyproject.toml) when available; this image doesn't bake
# it in, so fall back to a byte-compile pass rather than failing the
# target on a missing tool
lint:
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check spicedb_kubeapi_proxy_tpu tests bench.py; \
	elif $(PY) -c "import ruff" >/dev/null 2>&1; then \
	  $(PY) -m ruff check spicedb_kubeapi_proxy_tpu tests bench.py; \
	else \
	  echo "ruff not installed; falling back to compileall"; \
	  $(PY) -m compileall -q spicedb_kubeapi_proxy_tpu tests bench.py; \
	fi

# the invariant lint suite (tools/analysis/): five AST passes encoding
# the bug classes earlier review rounds fixed by hand — loop-blocking,
# lock-discipline, fail-closed, jit-stability, metrics-contract — as a
# hard gate. Zero unallowlisted findings or the build fails; intent is
# recorded per finding in tools/analysis/allowlist.txt. See
# docs/development.md.
analyze:
	$(PY) tools/analysis/run.py --strict

# the one command matching the harness: lint + the tier-1 pytest line
# from ROADMAP.md (same flags, same timeout, same pass-count echo).
# CHAOS=1 additionally runs the failpoint chaos suite first (a superset
# of what tier-1 already selects, but isolated: chaos failures surface
# on their own before the big run).
verify: lint analyze
	@if [ "$(CHAOS)" = "1" ]; then $(MAKE) chaos; fi
	$(PY) -m pytest -q -p no:cacheprovider tests/test_caveats.py
	$(PY) -m pytest -q -p no:cacheprovider tests/test_scaleout.py
	$(PY) -m pytest -q -p no:cacheprovider tests/test_rebalance.py
	$(PY) -m pytest -q -p no:cacheprovider tests/test_autoscale.py
	$(PY) -m pytest -q -p no:cacheprovider tests/test_tiered.py
	$(PY) -m pytest -q -p no:cacheprovider tests/test_migration.py
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$$?; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

clean:
	rm -f spicedb_kubeapi_proxy_tpu/native/libgraphcore.so
	find . -name __pycache__ -type d -exec rm -rf {} +

# flake hunting: loop the suite until it fails (reference
# `mage test:e2eUntilItFails`)
test-until-it-fails:
	while $(PY) -m pytest tests/ -q; do echo "=== pass, again ==="; done
