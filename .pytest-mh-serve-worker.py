
import os, sys
role, port_coord, port_tcp, repo = (sys.argv[1], sys.argv[2], sys.argv[3],
                                    sys.argv[4])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, repo)
import jax
jax.config.update("jax_platforms", "cpu")
from spicedb_kubeapi_proxy_tpu.engine.remote import main

pid = "0" if role == "leader" else "1"
argv = ["--distributed", f"127.0.0.1:{port_coord},2,{pid}",
        "--engine-mesh", "auto", "--token", "mh-tok",
        "--engine-insecure"]  # loopback-only test fixture
if role == "leader":
    argv += ["--bind-port", port_tcp]
    print("LEADER STARTING", flush=True)
else:
    argv += ["--mirror-leader", f"127.0.0.1:{port_tcp}",
             "--bind-port", "0"]
    print("FOLLOWER STARTING", flush=True)
sys.exit(main(argv))
